"""Forward / gradient-descent base units for the NN layer library.

Re-creation of the absent ``veles.znicz.nn_units`` (ForwardBase /
GradientDescentBase — SURVEY.md §2.9; solver/regularization knobs per
/root/reference/docs/source/manualrst_veles_algorithms.rst:150-165).

TPU-first contract: every Forward implements

- ``init_params()`` — allocate weights/bias host-side with the unit's
  reproducible :class:`RandomGenerator` (reference replays RandomState per
  unit, units.py:859-885);
- ``apply(params, x)`` — a *pure* function of ``params = {"weights": W,
  "bias": b}`` usable under jit/grad/vmap/shard_map.  Graph-mode ``run``
  wraps it; the StandardWorkflow fused step composes the whole chain of
  ``apply``s into one jitted train step with ``jax.value_and_grad``.

Every GradientDescent unit implements explicit backward math (``numpy_run``
twin + jitted kernel) so graph mode matches the fused autodiff path — that
equivalence is asserted by the tests.
"""

import numpy

from ..accelerated_units import AcceleratedUnit
from ..memory import Array
from .. import prng
from . import solvers


import threading as _threading

_oracle_only_state = _threading.local()


class oracle_only:
    """Context manager forcing every Pallas-capable unit onto its pure
    XLA/jnp formulation while tracing (regardless of knobs).  Used by
    the exporter: a Mosaic ``tpu_custom_call`` baked into a StableHLO
    artifact would break the package's any-backend portability
    contract (export/loader.py).  Thread-LOCAL: an export on one
    thread must not flip concurrent traces (e.g. a training retrace)
    on other threads onto the slower oracle path."""

    def __enter__(self):
        _oracle_only_state.depth = getattr(
            _oracle_only_state, "depth", 0) + 1
        return self

    def __exit__(self, *exc):
        _oracle_only_state.depth -= 1
        return False


def resolve_use_pallas(setting, device, tpu_auto):
    """Shared tri-state ``use_pallas`` semantics for every
    Pallas-capable unit: True/False force the choice; None (unset) =
    AUTO — the per-unit measured best, which is ``tpu_auto`` when the
    unit's device is the TPU and False elsewhere (CPU interpret-mode
    kernels are orders slower; docs/PERF.md carries the per-kernel
    measurements: flash attention wins on TPU, the LRN pair loses).
    Inside :class:`oracle_only` everything resolves False."""
    if getattr(_oracle_only_state, "depth", 0):
        return False
    if setting is not None:
        return bool(setting)
    if not tpu_auto:
        return False
    backend = getattr(device, "BACKEND", None)
    if backend is None:  # unit not initialized (direct apply/trace)
        import jax
        # mirror AutoDevice.pick: anything that is not the CPU platform
        # (tpu, or a tunneled transport like axon) counts as the TPU —
        # otherwise units traced without a device on such platforms
        # would take the O(T^2) oracle instead of flash attention
        return jax.default_backend() != "cpu"
    return backend == "tpu"


class NNUnitBase(AcceleratedUnit):
    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.prng = kwargs.get("prng", prng.get())


class ForwardBase(NNUnitBase):
    """Base for forward propagation units (weights + bias + activation)."""

    hide_from_registry = True
    view_group = "WORKER"
    MAPPING = None  # StandardWorkflow layer-type key
    #: True for units whose train-time forward draws randomness (dropout,
    #: stochastic pooling) — they implement apply_train(params, x, key);
    #: the key ARRIVES AS AN ARGUMENT so jit never freezes the draw
    stochastic = False

    def export_params(self):
        """Structural hyperparameters for the package archive — what the
        native engine needs to rebuild this unit (reference libVeles
        Unit::SetParameter from contents.json, unit.h:87-92)."""
        return {}

    def apply_train(self, params, x, key=None):
        """Train-time forward; defaults to the eval forward.  Stochastic
        units override and consume ``key``."""
        return self.apply(params, x)

    #: stochastic units hold a KeyTree; graph mode draws one key per train
    #: minibatch and records it so the matching backward can regenerate
    #: the same draw (no mask storage needed)
    key_tree = None
    minibatch_class = None   # linked from the loader for stochastic units

    def _graph_training(self):
        from .. import loader as loader_mod
        return self.stochastic and \
            self.minibatch_class == loader_mod.TRAIN

    def step_key(self):
        self._last_key_ = self.key_tree.key_for(self.name)
        return self._last_key_

    @property
    def last_key(self):
        return getattr(self, "_last_key_", None)

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.input = None               # linked from the previous unit
        self.output = Array()
        self.weights = Array()
        self.bias = Array()
        self.include_bias = bool(kwargs.get("include_bias", True))
        self.weights_stddev = kwargs.get("weights_stddev")
        self.bias_stddev = kwargs.get("bias_stddev",
                                      kwargs.get("weights_stddev"))
        self.weights_filling = kwargs.get("weights_filling", "uniform")
        self.bias_filling = kwargs.get("bias_filling", "uniform")
        # include_bias is structural config (export_params), not a tensor
        self.exports = ["weights", "bias"]

    # -- parameter handling --------------------------------------------------
    @property
    def params(self):
        """The layer's trainable pytree (device views)."""
        p = {}
        if self.weights:
            p["weights"] = self.weights.devmem
        if self.include_bias and self.bias:
            p["bias"] = self.bias.devmem
        return p

    def set_params(self, params):
        """Accept fresh device values from the fused step."""
        if "weights" in params:
            self.weights.devmem = params["weights"]
        if "bias" in params:
            self.bias.devmem = params["bias"]

    @property
    def host_params(self):
        """Host (numpy) twin of :attr:`params` — the numpy backend and
        the GD host path read through this, so units with extra
        parameter tensors (attention's ``proj``) override params/
        host_params as a pair."""
        p = {}
        if self.weights:
            p["weights"] = self.weights.map_read()
        if self.include_bias and self.bias:
            p["bias"] = self.bias.map_read()
        return p

    def set_host_params(self, params):
        if "weights" in params:
            self.weights.mem = numpy.asarray(params["weights"],
                                             numpy.float32)
        if "bias" in params:
            self.bias.mem = numpy.asarray(params["bias"], numpy.float32)

    def fill_array(self, arr, shape, stddev, filling):
        n_in = shape[0] if len(shape) > 1 else max(shape[0], 1)
        if stddev is None:
            stddev = 1.0 / numpy.sqrt(n_in)
        mem = numpy.zeros(shape, numpy.float32)
        if filling == "uniform":
            self.prng.fill(mem, -stddev, stddev)
        elif filling == "gaussian":
            mem[...] = self.prng.normal(0, stddev, shape)
        elif filling == "constant":
            mem[...] = stddev
        else:
            raise ValueError("unknown filling %r" % filling)
        arr.mem = mem

    def init_params(self):
        raise NotImplementedError

    def apply(self, params, x):
        raise NotImplementedError

    # -- graph-mode execution ------------------------------------------------
    def output_shape_for(self, input_shape):
        """Shape of the output for a given input shape; lets initialize
        pre-allocate ``output`` so downstream units can size themselves
        before the first run (reference forwards allocate in initialize)."""
        raise NotImplementedError

    #: methods every concrete forward must implement (verified at
    #: initialize — reference verified.py contract role)
    CONTRACT = ("apply", "output_shape_for")

    def initialize(self, device=None, **kwargs):
        from ..verified import verify_contract
        verify_contract(self, ForwardBase)
        super().initialize(device=device, **kwargs)
        if not self.weights:
            self.init_params()
        out_shape = self.output_shape_for(self.input_shape)
        if not self.output or tuple(self.output.shape) != tuple(out_shape):
            self.output.reset(numpy.zeros(out_shape, numpy.float32))

    @property
    def input_shape(self):
        v = self.input
        return v.shape if isinstance(v, Array) else numpy.shape(v)

    def tpu_init(self):
        import jax
        self._jitted_ = jax.jit(self.apply)
        if self.stochastic:
            self._jitted_train_ = jax.jit(self.apply_train)

    def make_trace(self):
        """Generic forward face: ``apply(params, x)`` is already the pure
        function graph-compilation needs; the params ride the region's
        donated carry (shared, by key, with the GD unit that updates
        them).  Stochastic forwards draw per-minibatch keys host-side and
        stay interpreted."""
        from ..graphcomp.faces import (NoFace, TraceFace,
                                       forward_params_leaf)
        if self.stochastic:
            return NoFace("stochastic forward (host-side per-minibatch "
                          "key draws)")
        if type(self).tpu_run is not ForwardBase.tpu_run:
            return NoFace("custom tpu_run (side effects beyond the pure "
                          "apply)")
        if not self._initialized:
            return NoFace("unit not initialized")
        if getattr(self, "_backend_run_", None) != self.tpu_run:
            return NoFace("numpy backend (no jitted path)")
        state = (forward_params_leaf(self),) if self.params else ()

        def fn(state_in, inputs, statics):
            return {}, {"output": self.apply(state_in.get("params", {}),
                                             inputs["input"])}
        return TraceFace(self, fn, inputs=("input",), outputs=("output",),
                         state=state, sync_attrs=("weights", "bias"))

    def tpu_run(self):
        x = self.input.devmem if isinstance(self.input, Array) else self.input
        if self._graph_training():
            self.output.devmem = self._jitted_train_(
                self.params, x, self.step_key())
        else:
            self.output.devmem = self._jitted_(self.params, x)

    def numpy_run(self):
        x = self.input.map_read() if isinstance(self.input, Array) \
            else numpy.asarray(self.input)
        params = self.host_params
        if self._graph_training():
            # replay the device draw exactly on host (jnp on CPU)
            self.output.mem = numpy.asarray(
                self.apply_train(params, x, self.step_key()))
        else:
            self.output.mem = numpy.asarray(self.apply_numpy(params, x))

    def apply_numpy(self, params, x):
        """Host twin; default falls back to the jnp apply (exact on CPU)."""
        return self.apply(params, x)


class ParamlessForward(ForwardBase):
    """Base for forwards with no trainable parameters (pooling, dropout,
    activations, structural units)."""

    hide_from_registry = True

    def init_params(self):
        pass

    @property
    def params(self):
        return {}

    def set_params(self, params):
        pass

    def output_shape_for(self, input_shape):
        return tuple(input_shape)


class GradientDescentBase(NNUnitBase):
    """Base for backward/update units.

    Linked attributes (reference GD contract): ``input`` (forward's input),
    ``output`` (forward's output), ``err_output`` (gradient flowing in from
    the next layer or the evaluator); produces ``err_input`` and updates the
    forward's ``weights``/``bias`` in place through a two-way link.
    """

    hide_from_registry = True
    view_group = "TRAINER"
    MAPPING = None

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.input = None
        self.output = None
        self.err_output = None
        self.batch_size = None     # linked: loader.minibatch_size (valid
        #                            rows; padded rows carry zero err)
        self.err_input = Array()
        self.weights = None        # linked two-way with the forward
        self.bias = None
        self.forward_unit = None   # set by link_forward / StandardWorkflow
        self.learning_rate = kwargs.get("learning_rate", 0.01)
        self.learning_rate_bias = kwargs.get("learning_rate_bias",
                                             kwargs.get("learning_rate",
                                                        0.01))
        self.weights_decay = kwargs.get("weights_decay", 0.0)
        self.weights_decay_bias = kwargs.get("weights_decay_bias", 0.0)
        self.l1_vs_l2 = kwargs.get("l1_vs_l2", 0.0)
        self.l1_vs_l2_bias = kwargs.get("l1_vs_l2_bias",
                                        kwargs.get("l1_vs_l2", 0.0))
        self.factor_ortho = kwargs.get("factor_ortho", 0.0)
        self.gradient_moment = kwargs.get("gradient_moment", 0.0)
        self.solver_name = kwargs.get(
            "solver", "momentum" if self.gradient_moment else "sgd")
        hyper = dict(kwargs.get("solver_parameters", {}))
        if self.solver_name == "momentum":
            hyper.setdefault("momentum", self.gradient_moment or 0.9)
        self.solver = solvers.factory(self.solver_name, **hyper)
        self.solver_state = {}     # param name -> state tuple
        self.need_err_input = bool(kwargs.get("need_err_input", True))
        self.batch_normalize_grad = False

    def link_forward(self, fwd):
        """Wire the standard attribute set to a forward unit."""
        self.forward_unit = fwd
        self.link_attrs(fwd, "input", "output", two_way=False)
        self.link_attrs(fwd, "weights", "bias", two_way=True)
        return self

    # -- solver plumbing -----------------------------------------------------
    def ensure_solver_state(self, params, xp=numpy):
        for name, p in params.items():
            if name not in self.solver_state:
                self.solver_state[name] = self.solver.init(p, xp)

    def lr_for(self, name):
        return self.learning_rate_bias if name == "bias" \
            else self.learning_rate

    def decay_for(self, name):
        if name == "bias":
            return self.weights_decay_bias, self.l1_vs_l2_bias, 0.0
        return self.weights_decay, self.l1_vs_l2, self.factor_ortho

    def apply_updates(self, params, grads, xp=numpy):
        """Pure-ish solver application; returns new params dict and stores
        new solver state."""
        self.ensure_solver_state(params, xp)
        out = {}
        for name, p in params.items():
            g = grads[name]
            decay, l1l2, ortho = self.decay_for(name)
            g = solvers.regularized_grad(g, p, decay, l1l2, xp, ortho)
            delta, new_state = self.solver.update(
                g, p, self.solver_state[name], self.lr_for(name), xp)
            self.solver_state[name] = new_state
            out[name] = p + delta
        return out

    # -- backward interface --------------------------------------------------
    def backward(self, params, x, y, err_output, n_valid=None):
        """Pure backward: returns (err_input, grads dict).  Gradients are
        the mean over the *valid* rows (padded rows carry zero error)."""
        raise NotImplementedError

    def backward_via_vjp(self, params, x, err_output, n_valid):
        """Generic backward through jax.vjp of the forward's pure apply —
        the exact chain rule the fused path uses, so graph mode and fused
        mode agree by construction.  Units with hand-written backward math
        (the all2all family) override ``backward`` directly; structured ops
        (conv, pooling, LRN) use this."""
        import jax
        fwd = self.forward_unit
        _, pullback = jax.vjp(lambda p, xx: fwd.apply(p, xx), params, x)
        grads, err_input = pullback(err_output)
        grads = jax.tree.map(lambda g: g / n_valid, grads)
        return err_input, grads

    def _n_valid(self, x):
        return int(self.batch_size) if self.batch_size is not None \
            else x.shape[0]

    def _gather_params(self, host):
        """The forward's FULL param dict (overridable shapes like
        attention's ``proj`` included); hardcoded weights/bias only when
        no forward is linked (hand-built test graphs)."""
        fwd = self.forward_unit
        if fwd is not None:
            return dict(fwd.host_params if host else fwd.params)
        if host:
            params = {"weights": self._host(self.weights)}
            if self.bias:
                params["bias"] = self._host(self.bias)
            return params
        params = {"weights": self.weights.devmem}
        if self.bias:
            params["bias"] = self.bias.devmem
        return params

    def _store_params(self, new_params, host):
        fwd = self.forward_unit
        if fwd is not None:
            (fwd.set_host_params if host else fwd.set_params)(new_params)
            return
        if host:
            self.weights.mem = numpy.asarray(new_params["weights"],
                                             numpy.float32)
            if self.bias and "bias" in new_params:
                self.bias.mem = numpy.asarray(new_params["bias"],
                                              numpy.float32)
        else:
            self.weights.devmem = new_params["weights"]
            if self.bias and "bias" in new_params:
                self.bias.devmem = new_params["bias"]

    def numpy_run(self):
        x = self._host(self.input)
        y = self._host(self.output)
        err_out = self._host(self.err_output)
        params = self._gather_params(host=True)
        err_in, grads = self.backward_numpy(params, x, y, err_out,
                                            self._n_valid(x))
        new_params = self.apply_updates(params, grads, numpy)
        self._store_params(new_params, host=True)
        if self.need_err_input:
            self.err_input.mem = numpy.asarray(err_in, numpy.float32)

    def backward_numpy(self, params, x, y, err_output, n_valid=None):
        return self.backward(params, x, y, err_output, n_valid)

    def tpu_init(self):
        import jax
        # n_valid stays static (bounded set of sizes → bounded retraces)
        self._jitted_bwd_ = jax.jit(self.backward, static_argnames="n_valid")
        # backward + regularizer + solver update as ONE jit: one dispatch
        # per GD run instead of jit(backward) plus ~6 eager solver ops
        # per parameter, and — critically — the exact function the graph
        # compiler composes into whole-workflow programs, so traced and
        # interpreted dispatch are bitwise-identical by construction.
        # Learning rates ride as ARGUMENTS (LearningRateAdjuster mutates
        # them per epoch without retracing); decay/solver hyperparameters
        # are closed over and fingerprinted by the face's config key.
        self._jitted_step_ = jax.jit(self._device_step,
                                     static_argnames="n_valid")

    def _device_step(self, params, solver_state, x, y, err_output, lr,
                     lr_bias, n_valid):
        """Pure fused backward: (params', solver_state', err_input)."""
        import jax.numpy as jnp
        err_in, grads = self.backward(params, x, y, err_output,
                                      n_valid=n_valid)
        new_params, new_state = {}, {}
        for name, p in params.items():
            g = grads[name]
            decay, l1l2, ortho = self.decay_for(name)
            g = solvers.regularized_grad(g, p, decay, l1l2, jnp, ortho)
            delta, st = self.solver.update(
                g, p, solver_state[name],
                lr_bias if name == "bias" else lr, jnp)
            new_params[name] = p + delta
            new_state[name] = st
        return new_params, new_state, err_in

    def tpu_run(self):
        import numpy
        import jax.numpy as jnp
        x = self._dev(self.input)
        y = self._dev(self.output)
        err_out = self._dev(self.err_output)
        params = self._gather_params(host=False)
        if getattr(self, "_jitted_step_", None) is None:
            # subclasses overriding tpu_init (dropout, stochastic
            # pooling) keep the classic jit(backward) + eager-update path
            err_in, grads = self._jitted_bwd_(params, x, y, err_out,
                                              n_valid=self._n_valid(x))
            new_params = self.apply_updates(params, grads, jnp)
        else:
            self.ensure_solver_state(params, jnp)
            state = {n: self.solver_state[n] for n in params}
            new_params, new_state, err_in = self._jitted_step_(
                params, state, x, y, err_out,
                numpy.float32(self.learning_rate),
                numpy.float32(self.learning_rate_bias),
                n_valid=self._n_valid(x))
            for n, st in new_state.items():
                self.solver_state[n] = st
        self._store_params(new_params, host=False)
        if self.need_err_input:
            self.err_input.devmem = err_in

    def make_trace(self):
        """Generic GD face: composes :meth:`_device_step` — the SAME
        function the interpreted path jits — into the region program;
        params are shared (by key) with the linked forward, solver state
        is this unit's own carry synced back into ``solver_state``."""
        from ..graphcomp.faces import (NoFace, TraceFace, forward_params_leaf,
                                       gd_params_leaf, solver_state_leaf)
        if type(self).tpu_init is not GradientDescentBase.tpu_init:
            return NoFace("custom backward path (per-minibatch host "
                          "state)")
        if type(self).tpu_run is not GradientDescentBase.tpu_run:
            return NoFace("custom tpu_run")
        if type(self).apply_updates is not GradientDescentBase.apply_updates:
            return NoFace("custom update rule")
        if not self._initialized:
            return NoFace("unit not initialized")
        if getattr(self, "_backend_run_", None) != self.tpu_run:
            return NoFace("numpy backend (no jitted path)")
        fwd = self.forward_unit
        state = []
        if fwd is not None and fwd.params:
            state.append(forward_params_leaf(fwd))
        elif fwd is None and self.weights:
            state.append(gd_params_leaf(self))
        if state:
            params_of = (lambda: dict(fwd.params)) if fwd is not None \
                else (lambda: self._gather_params(host=False))
            state.append(solver_state_leaf(self, params_of))
        outputs = ("err_input",) if self.need_err_input else ()
        config = (self.decay_for("weights"), self.decay_for("bias"),
                  self.solver_name,
                  tuple(sorted(self.solver.hyper.items())),
                  self.need_err_input)

        def fn(state_in, inputs, statics):
            n_valid = statics["batch_size"]
            if n_valid is None:
                n_valid = inputs["input"].shape[0]
            new_p, new_s, err_in = self._device_step(
                state_in.get("params", {}), state_in.get("solver", {}),
                inputs["input"], inputs["output"], inputs["err_output"],
                inputs["learning_rate"], inputs["learning_rate_bias"],
                int(n_valid))
            updates = {"params": new_p, "solver": new_s} if new_p else {}
            outs = {"err_input": err_in} if self.need_err_input else {}
            return updates, outs
        return TraceFace(
            self, fn,
            inputs=("input", "output", "err_output", "learning_rate",
                    "learning_rate_bias"),
            statics=("batch_size",), outputs=outputs, state=tuple(state),
            config=config)

    @staticmethod
    def _host(v):
        if isinstance(v, Array):
            return v.map_read()
        return numpy.asarray(v)

    @staticmethod
    def _dev(v):
        if isinstance(v, Array):
            return v.devmem
        return v


class GenericVJPBackward(GradientDescentBase):
    """Fallback backward for layer types without a registered GD pair
    (structural units: splitters, depooling, ...): pure vjp pass-through
    of the forward, no parameters."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("learning_rate", 0.0)
        super().__init__(workflow, **kwargs)

    def backward(self, params, x, y, err_output, n_valid=None):
        if n_valid is None:
            n_valid = x.shape[0]
        err_in, _ = self.backward_via_vjp({}, x, err_output, n_valid)
        return err_in, {}

    def backward_numpy(self, params, x, y, err_output, n_valid=None):
        err_in, grads = self.backward(params, x, y, err_output, n_valid)
        return numpy.asarray(err_in), grads
