"""Gradient-descent units for the conv family.

Re-creation of ``veles.znicz.gd_conv`` (absent; SURVEY.md §2.9):
GradientDescentConv + activation variants.  Backward runs through
``jax.vjp`` of the forward (XLA emits the transpose conv for err_input and
the correlation for grad_W — the two kernels the reference hand-writes),
sharing solver machinery with the all2all GD units.
"""

from .nn_units import GradientDescentBase


class GradientDescentConv(GradientDescentBase):
    MAPPING = "conv"

    def backward(self, params, x, y, err_output, n_valid=None):
        if n_valid is None:
            n_valid = x.shape[0]
        return self.backward_via_vjp(params, x, err_output, n_valid)

    def backward_numpy(self, params, x, y, err_output, n_valid=None):
        import numpy
        if n_valid is None:
            n_valid = x.shape[0]
        err_in, grads = self.backward(params, x, y, err_output, n_valid)
        return (numpy.asarray(err_in) if err_in is not None else None,
                {k: numpy.asarray(v) for k, v in grads.items()})


class GDTanhConv(GradientDescentConv):
    MAPPING = "conv_tanh"


class GDSigmoidConv(GradientDescentConv):
    MAPPING = "conv_sigmoid"


class GDRELUConv(GradientDescentConv):
    MAPPING = "conv_relu"


class GDStrictRELUConv(GradientDescentConv):
    MAPPING = "conv_str"
