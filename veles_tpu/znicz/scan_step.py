"""ScanEpochStep: one XLA dispatch per dataset class via ``lax.scan``.

The fused per-minibatch step (fused.py) still pays one host→device dispatch
per minibatch — on a tunneled/remote TPU that RTT (~1 ms) dominates small
models.  This unit collapses an ENTIRE class (all train minibatches, or all
validation minibatches) into one jitted ``lax.scan``:

    (params, opt, macc) = scan(body, init, (idx_matrix, sizes))

with the resident FullBatch dataset gathered per-iteration *inside* the
scan (``jnp.take``), masks built from the per-batch ``sizes`` vector, so
results are bit-identical to the per-step path (asserted in tests).  Host
work per class: build the index matrix (numpy), one device_put, one
dispatch, one metric flush.

The unit replaces loader+fused_step in the control graph (repeater →
scan_step → decision); the Loader still owns the dataset, shuffling, and
epoch counters — this unit drives its flags so Decision units observe the
exact same protocol (SURVEY.md §7: partition units into traced and host).
"""

import numpy

from ..units import Unit
from .. import loader as loader_mod
from .fused import FusedTrainStep


class ScanEpochStep(FusedTrainStep):
    """FusedTrainStep that consumes one whole class per ``run()``."""

    def __init__(self, workflow, forwards, gd_units, loss="softmax",
                 **kwargs):
        super().__init__(workflow, forwards, gd_units, loss=loss, **kwargs)
        self.loader = None          # set by link_scan_loader
        self._class_cursor = 0
        self._epochs_done = 0

    def link_scan_loader(self, loader):
        self.loader = loader
        # keep the attribute links Decision peeks at coherent
        self.link_loader(loader)
        return self

    def make_trace(self):
        """Epoch-scan composes with traced regions as a pre-compiled
        region of its own: one ``lax.scan`` dispatch already covers a
        whole class, so the graph compiler passes it through natively."""
        from ..graphcomp.faces import OpaqueFace
        return OpaqueFace(self, "epoch-scan step: one lax.scan dispatch "
                                "per dataset class")

    def initialize(self, device=None, **kwargs):
        if not self.loader.is_initialized:
            # normally the dependency walk has initialized the loader
            # already (it precedes this unit in the graph); this covers
            # hand-built workflows
            self.loader.initialize(device=device, **kwargs)
        super().initialize(device=device, **kwargs)
        import jax
        import jax.numpy as jnp
        from jax import lax

        train = self._train_step_.__wrapped__
        evaluate = self._eval_step_.__wrapped__
        # the resident dataset is an ARGUMENT of the jitted scans, not a
        # closure capture — a closed-over jax.Array becomes an HLO literal,
        # bloating the executable by the whole dataset (and overflowing
        # remote-compile transports on large sets)
        self._data_dev_ = self.loader.original_data.devmem
        if self.loss_kind == "softmax":
            self._y_dev_ = jax.device_put(self.loader._dense_labels)
        else:
            self._y_dev_ = self.loader.original_targets.devmem

        def train_scan(data_dev, y_dev, params, opt, macc, idx, sizes,
                       seeds, lr_scale):
            def body(carry, batch):
                p, o, m = carry
                bidx, bsize, bseed = batch
                x = self._constrain_batch(jnp.take(data_dev, bidx, axis=0))
                y = self._constrain_batch(jnp.take(y_dev, bidx, axis=0))
                p, o, m, loss, _ = train(p, o, m, x, y, bsize, bseed,
                                         lr_scale)
                return (p, o, m), loss
            (params, opt, macc), losses = lax.scan(
                body, (params, opt, macc), (idx, sizes, seeds))
            return params, opt, macc, losses

        def eval_scan(data_dev, y_dev, params, macc, idx, sizes):
            def body(m, batch):
                bidx, bsize = batch
                x = self._constrain_batch(jnp.take(data_dev, bidx, axis=0))
                y = self._constrain_batch(jnp.take(y_dev, bidx, axis=0))
                m, loss, _ = evaluate(params, m, x, y, bsize)
                return m, loss
            macc, losses = lax.scan(body, macc, (idx, sizes))
            return macc, losses

        self._train_scan_ = self._jit_train_scan(train_scan)
        self._eval_scan_ = self._jit_eval_scan(eval_scan)

    # -- sharding hooks (overridden by parallel.DistributedScanStep) --------
    def _constrain_batch(self, a):
        """Per-minibatch sharding constraint inside the scan body; the
        single-device step leaves arrays alone."""
        return a

    def _jit_train_scan(self, train_scan):
        import jax
        return jax.jit(train_scan, donate_argnums=(2, 3, 4))

    def _jit_eval_scan(self, eval_scan):
        import jax
        return jax.jit(eval_scan, donate_argnums=(3,))

    def _next_seeds(self, n):
        """Deterministic consecutive per-batch seeds (matches the per-step
        path's counter increments), wrapped to int32 range."""
        seeds = (numpy.arange(self._seed_counter + 1,
                              self._seed_counter + 1 + n,
                              dtype=numpy.int64) % 0x7FFF0000).astype(
            numpy.int32)
        self._seed_counter = (self._seed_counter + n) % 0x7FFF0000
        return seeds

    # -- epoch driving -------------------------------------------------------
    def _classes_with_samples(self):
        return [c for c in (loader_mod.TEST, loader_mod.VALID,
                            loader_mod.TRAIN)
                if self.loader.class_lengths[c] > 0]

    def _class_index_matrix(self, cls):
        """(idx_matrix[nb, B], sizes[nb]) over the class's shuffled span."""
        ld = self.loader
        start = 0 if cls == loader_mod.TEST else ld.class_end_offsets[
            cls - 1]
        end = ld._class_end(cls)
        span = numpy.asarray(ld.shuffled_indices.map_read()[start:end])
        B = ld.max_minibatch_size
        nb = (len(span) + B - 1) // B
        idx = numpy.empty((nb, B), ld.INDEX_DTYPE)
        sizes = numpy.empty(nb, numpy.int32)
        for i in range(nb):
            chunk = span[i * B:(i + 1) * B]
            sizes[i] = len(chunk)
            idx[i, :len(chunk)] = chunk
            if len(chunk) < B:
                idx[i, len(chunk):] = chunk[0]  # pad; masked by sizes
        return idx, sizes

    def run(self):
        ld = self.loader
        classes = self._classes_with_samples()
        if self._class_cursor == 0 and self._epochs_done:
            # same moment the per-step loader wraps: entering a new epoch
            ld.epoch_number += 1
            ld.shuffle()
        cls = classes[self._class_cursor]
        idx, sizes = self._class_index_matrix(cls)
        if cls == loader_mod.TRAIN:
            (self._params_, self._opt_, self._macc_, losses) = \
                self._train_scan_(self._data_dev_, self._y_dev_,
                                  self._params_, self._opt_, self._macc_,
                                  idx, sizes, self._next_seeds(len(sizes)),
                                  float(self.lr_scale))
        else:
            self._macc_, losses = self._eval_scan_(
                self._data_dev_, self._y_dev_,
                self._params_, self._macc_, idx, sizes)
        self.loss = losses[-1]
        ld.samples_served += int(sizes.sum())
        # drive the loader protocol so Decision sees normal class ends
        ld.minibatch_class = cls
        ld.minibatch_size = int(sizes[-1])
        last = self._class_cursor == len(classes) - 1
        self._class_cursor = 0 if last else self._class_cursor + 1
        ld.last_minibatch <<= True
        ld.train_ended <<= cls == loader_mod.TRAIN
        ld.valid_ended <<= cls == loader_mod.VALID
        ld.epoch_ended <<= last
        if last:
            self._epochs_done += 1
        self._flush_metrics()
        self.sync_weights()

    # -- bulk training -------------------------------------------------------
    def train_epochs(self, n_epochs):
        """Train ``n_epochs`` full TRAIN classes in ONE dispatch.

        Per-epoch shuffles are precomputed host-side and concatenated into
        one (n_epochs * nb, B) index tensor, so fixed-epoch bulk training
        (no per-epoch early stopping — the user trades Decision granularity
        for wall-clock) pays a single dispatch + a single metric read.
        On tunneled devices where any fresh device read costs ~90 ms this
        is the difference between 60k and >1M images/sec."""
        ld = self.loader
        chunks = []
        for _ in range(n_epochs):
            if self._epochs_done:
                ld.epoch_number += 1
                ld.shuffle()
            idx, sizes = self._class_index_matrix(loader_mod.TRAIN)
            chunks.append((idx, sizes))
            self._epochs_done += 1
        idx = numpy.concatenate([c[0] for c in chunks])
        sizes = numpy.concatenate([c[1] for c in chunks])
        (self._params_, self._opt_, self._macc_, losses) = \
            self._train_scan_(self._data_dev_, self._y_dev_,
                              self._params_, self._opt_, self._macc_,
                              idx, sizes, self._next_seeds(len(sizes)),
                              float(self.lr_scale))
        self.loss = losses[-1]
        ld.samples_served += int(sizes.sum())
        ld.minibatch_class = loader_mod.TRAIN
        self._flush_metrics()
        self.sync_weights()
