"""Structural layer units: Cutter, ChannelSplitter/Merger, ZeroFiller,
Deconv.

Re-creation of the remaining Znicz layer inventory (absent submodule;
SURVEY.md §2.9): ``cutter.Cutter/GDCutter``,
``channel_splitting.ChannelSplitter/Merger``,
``weights_zerofilling.ZeroFiller``, ``deconv.Deconv/gd_deconv.GDDeconv``,
``depooling.Depooling``.
"""

import numpy

from .nn_units import (ForwardBase, GradientDescentBase,
                       ParamlessForward as _ParamlessForward)
from .conv import _quad


class Cutter(_ParamlessForward):
    """Crops a spatial region: y = x[:, top:top+h, left:left+w, :]."""

    MAPPING = "cutter"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.top = kwargs.get("top", 0)
        self.left = kwargs.get("left", 0)
        self.crop_h = kwargs["crop_h"]
        self.crop_w = kwargs["crop_w"]
        self.include_bias = False

    def output_shape_for(self, input_shape):
        return (input_shape[0], self.crop_h, self.crop_w, input_shape[3])

    def apply(self, params, x):
        return x[:, self.top:self.top + self.crop_h,
                 self.left:self.left + self.crop_w, :]

    apply_numpy = apply


class GDCutter(GradientDescentBase):
    """Backward: pad the error back into the uncropped shape."""

    MAPPING = "cutter"

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("learning_rate", 0.0)
        super().__init__(workflow, **kwargs)

    def backward(self, params, x, y, err_output, n_valid=None):
        import jax.numpy as jnp
        cut = self.forward_unit
        pads = ((0, 0),
                (cut.top, x.shape[1] - cut.top - cut.crop_h),
                (cut.left, x.shape[2] - cut.left - cut.crop_w),
                (0, 0))
        return jnp.pad(err_output, pads), {}

    def backward_numpy(self, params, x, y, err_output, n_valid=None):
        cut = self.forward_unit
        pads = ((0, 0),
                (cut.top, x.shape[1] - cut.top - cut.crop_h),
                (cut.left, x.shape[2] - cut.left - cut.crop_w),
                (0, 0))
        return numpy.pad(err_output, pads), {}


class ChannelSplitter(_ParamlessForward):
    """NHWC → list of per-group tensors stacked on a new axis (the Znicz
    unit splits interleaved channels for grouped convolutions)."""

    MAPPING = "channel_splitter"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.groups = kwargs.get("groups", 2)
        self.include_bias = False

    def output_shape_for(self, input_shape):
        b, h, w, c = input_shape
        return (self.groups, b, h, w, c // self.groups)

    def apply(self, params, x):
        b, h, w, c = x.shape
        g = self.groups
        return x.reshape(b, h, w, g, c // g).transpose(3, 0, 1, 2, 4)

    apply_numpy = apply


class ChannelMerger(_ParamlessForward):
    """Inverse of ChannelSplitter."""

    MAPPING = "channel_merger"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.include_bias = False

    def output_shape_for(self, input_shape):
        g, b, h, w, cg = input_shape
        return (b, h, w, g * cg)

    def apply(self, params, x):
        g, b, h, w, cg = x.shape
        return x.transpose(1, 2, 3, 0, 4).reshape(b, h, w, g * cg)

    apply_numpy = apply


class ZeroFiller(_ParamlessForward):
    """Zeroes a fixed mask of weights in an attached forward unit every
    run (the Znicz grouping trick for AlexNet's split convolutions).

    GRAPH MODE ONLY: in fused mode ``run()`` never fires (forwards live
    outside the control graph) and the masking would not reach the fused
    params — use the native ``Conv(grouping=N)`` instead, which is both
    correct under fusion and faster (XLA grouped conv).  StandardWorkflow
    raises if a zero_filler layer appears in a fused build."""

    MAPPING = "zero_filler"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.target_unit = kwargs.get("target_unit")
        self.grouping = kwargs.get("grouping", 2)
        self.include_bias = False

    def output_shape_for(self, input_shape):
        return tuple(input_shape)

    def apply(self, params, x):
        return x

    apply_numpy = apply

    def make_mask(self, weights_shape):
        """Block-diagonal channel mask: group g of kernels sees only group
        g of input channels."""
        ky, kx, c_in, n_k = weights_shape
        g = self.grouping
        mask = numpy.zeros(weights_shape, numpy.float32)
        for i in range(g):
            mask[:, :, i * (c_in // g):(i + 1) * (c_in // g),
                 i * (n_k // g):(i + 1) * (n_k // g)] = 1
        return mask

    def run(self):
        if self.target_unit is not None and self.target_unit.weights:
            w = self.target_unit.weights.map_write()
            w *= self.make_mask(w.shape)


class Deconv(ForwardBase):
    """Transposed convolution (conv autoencoder decoder; reference
    deconv.Deconv)."""

    MAPPING = "deconv"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.n_kernels = kwargs["n_kernels"]    # output channels
        self.kx = kwargs["kx"]
        self.ky = kwargs["ky"]
        self.padding = _quad(kwargs.get("padding", 0))
        self.sliding = tuple(kwargs.get("sliding", (1, 1)))
        self.include_bias = bool(kwargs.get("include_bias", False))

    def init_params(self):
        c_in = self.input_shape[-1]
        n_in = self.kx * self.ky * c_in
        stddev = self.weights_stddev or 1.0 / numpy.sqrt(n_in)
        self.fill_array(self.weights,
                        (self.ky, self.kx, c_in, self.n_kernels),
                        stddev, self.weights_filling)
        if self.include_bias:
            self.fill_array(self.bias, (self.n_kernels,),
                            self.bias_stddev or stddev, self.bias_filling)

    def output_shape_for(self, input_shape):
        b, h, w, _ = input_shape
        pt, pb, pl, pr = self.padding
        oh = (h - 1) * self.sliding[0] + self.ky - pt - pb
        ow = (w - 1) * self.sliding[1] + self.kx - pl - pr
        return (b, oh, ow, self.n_kernels)

    def apply(self, params, x):
        from jax import lax
        pt, pb, pl, pr = self.padding
        y = lax.conv_transpose(
            x, params["weights"],
            strides=self.sliding,
            padding=((pt, pb), (pl, pr)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if "bias" in params:
            y = y + params["bias"]
        return y

    def apply_numpy(self, params, x):
        return numpy.asarray(self.apply(
            {k: numpy.asarray(v) for k, v in params.items()}, x))


class GDDeconv(GradientDescentBase):
    MAPPING = "deconv"

    def backward(self, params, x, y, err_output, n_valid=None):
        if n_valid is None:
            n_valid = x.shape[0]
        return self.backward_via_vjp(params, x, err_output, n_valid)

    def backward_numpy(self, params, x, y, err_output, n_valid=None):
        err_in, grads = self.backward(params, x, y, err_output, n_valid)
        return (numpy.asarray(err_in) if err_in is not None else None,
                {k: numpy.asarray(v) for k, v in grads.items()})


class Depooling(_ParamlessForward):
    """Nearest upsampling by the pooling window (reference
    depooling.Depooling used in conv AEs)."""

    MAPPING = "depooling"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.kx = kwargs.get("kx", 2)
        self.ky = kwargs.get("ky", 2)
        self.include_bias = False

    def output_shape_for(self, input_shape):
        b, h, w, c = input_shape
        return (b, h * self.ky, w * self.kx, c)

    def apply(self, params, x):
        import jax.numpy as jnp
        return jnp.repeat(jnp.repeat(x, self.ky, axis=1), self.kx, axis=2)

    def apply_numpy(self, params, x):
        return numpy.repeat(numpy.repeat(x, self.ky, axis=1),
                            self.kx, axis=2)
