"""Convolutional forward units.

Re-creation of ``veles.znicz.conv`` (absent; inventory SURVEY.md §2.9;
parameters n_kernels/kx/ky/padding/sliding per
/root/reference/docs/source/manualrst_veles_workflow_parameters.rst:421-436).

TPU-first: NHWC activations, HWIO weights, one
``lax.conv_general_dilated`` — the exact op XLA tiles onto the MXU; the
activation fuses into its epilogue.  The numpy twin is an independent
im2col implementation (the same construction the reference's GPU kernels
use) so the parity tests cross-check two different algorithms.
"""

import numpy

from .nn_units import ForwardBase
from . import activations


def _quad(padding):
    """Normalize padding to (top, bottom, left, right)."""
    if isinstance(padding, int):
        return (padding,) * 4
    if len(padding) == 2:
        py, px = padding
        return (py, py, px, px)
    return tuple(padding)


class Conv(ForwardBase):
    """2-D convolution + activation.  Input NHWC; weights (kx, ky, C, K)."""

    MAPPING = "conv"
    ACTIVATION = "linear"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.n_kernels = kwargs["n_kernels"]
        self.kx = kwargs["kx"]
        self.ky = kwargs["ky"]
        self.padding = _quad(kwargs.get("padding", 0))
        self.sliding = tuple(kwargs.get("sliding", (1, 1)))
        # grouped convolution (AlexNet's two-tower split): native
        # feature_group_count — faster than the reference's ZeroFiller
        # weight-masking trick, same math
        self.grouping = int(kwargs.get("grouping", 1))
        self.activation = activations.get(self.ACTIVATION)

    def init_params(self):
        c_in = self.input_shape[-1]
        n_in = self.kx * self.ky * c_in // self.grouping
        stddev = self.weights_stddev or 1.0 / numpy.sqrt(n_in)
        self.fill_array(self.weights,
                        (self.ky, self.kx, c_in // self.grouping,
                         self.n_kernels),
                        stddev, self.weights_filling)
        if self.include_bias:
            self.fill_array(self.bias, (self.n_kernels,),
                            self.bias_stddev or stddev, self.bias_filling)

    def output_shape_for(self, input_shape):
        b, h, w, _ = input_shape
        pt, pb, pl, pr = self.padding
        oh = (h + pt + pb - self.ky) // self.sliding[0] + 1
        ow = (w + pl + pr - self.kx) // self.sliding[1] + 1
        return (b, oh, ow, self.n_kernels)

    def apply(self, params, x):
        import jax.numpy as jnp
        from jax import lax
        pt, pb, pl, pr = self.padding
        y = lax.conv_general_dilated(
            x, params["weights"],
            window_strides=self.sliding,
            padding=((pt, pb), (pl, pr)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=self.grouping)
        if "bias" in params:
            y = y + params["bias"]
        return self.activation.fwd_jnp(y)

    def apply_numpy(self, params, x):
        """Independent im2col twin (per-group)."""
        w = params["weights"]
        ky, kx, c_g, n_k = w.shape
        g = self.grouping
        pt, pb, pl, pr = self.padding
        sy, sx = self.sliding
        xp = numpy.pad(x, ((0, 0), (pt, pb), (pl, pr), (0, 0)))
        b, h, w_, c_in = xp.shape
        oh = (h - ky) // sy + 1
        ow = (w_ - kx) // sx + 1
        y = numpy.empty((b, oh, ow, n_k), x.dtype)
        kpg = n_k // g
        for gi in range(g):
            xg = xp[..., gi * c_g:(gi + 1) * c_g]
            wg = w[..., gi * kpg:(gi + 1) * kpg]
            cols = numpy.empty((b, oh, ow, ky * kx * c_g), x.dtype)
            for i in range(oh):
                for j in range(ow):
                    patch = xg[:, i * sy:i * sy + ky,
                               j * sx:j * sx + kx, :]
                    cols[:, i, j, :] = patch.reshape(b, -1)
            y[..., gi * kpg:(gi + 1) * kpg] = cols @ wg.reshape(-1, kpg)
        if "bias" in params:
            y = y + params["bias"]
        return self.activation.fwd_np(y)


    def export_params(self):
        return {"n_kernels": int(self.n_kernels), "kx": int(self.kx),
                "ky": int(self.ky), "padding": list(self.padding),
                "sliding": list(self.sliding),
                "grouping": int(self.grouping),
                "include_bias": bool(self.include_bias)}


class ConvTanh(Conv):
    MAPPING = "conv_tanh"
    ACTIVATION = "tanh"


class ConvSigmoid(Conv):
    MAPPING = "conv_sigmoid"
    ACTIVATION = "sigmoid"


class ConvRELU(Conv):
    """Znicz "RELU" = softplus."""
    MAPPING = "conv_relu"
    ACTIVATION = "relu"


class ConvStrictRELU(Conv):
    MAPPING = "conv_str"
    ACTIVATION = "strict_relu"
