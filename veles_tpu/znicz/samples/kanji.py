"""Kanji: glyph-denoising MSE workflow (target = clean class glyph).

Re-creation of the Znicz Kanji sample (absent submodule; named in the
reference's sample inventory, SURVEY.md §2.9).  The reference trained an
MLP to map distorted renderings of Japanese characters onto their CLEAN
target glyphs — an image→image MSE task where many noisy instances share
one target (loader/image_mse.py machinery).  Real font rendering needs
fontconfig assets the build env lacks; the loader synthesizes glyph
classes as deterministic stroke patterns, then emits jittered noisy
instances as inputs with the clean pattern as the MSE target — the same
many-to-one target structure.
"""

import numpy

from ...config import root
from ...loader.fullbatch import FullBatchLoaderMSE
from ...loader.base import TEST, VALID, TRAIN

_LR = {"learning_rate": 3e-3, "gradient_moment": 0.9}
SIDE = 24

root.kanji.update({
    "loader": {"minibatch_size": 50,
               "normalization_type": "range_linear",
               "target_normalization_type": "range_linear"},
    "layers": [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 120,
                                        "weights_stddev": 0.05},
         "<-": _LR},
        {"type": "all2all", "->": {"output_sample_shape": SIDE * SIDE,
                                   "weights_stddev": 0.05}, "<-": _LR},
    ],
    "decision": {"max_epochs": 40, "fail_iterations": 20},
})


def make_glyphs(n_classes, side=SIDE, seed=53):
    """Deterministic stroke-pattern 'glyphs', one per class."""
    rng = numpy.random.RandomState(seed)
    glyphs = numpy.zeros((n_classes, side, side), numpy.float32)
    for c in range(n_classes):
        for _ in range(rng.randint(3, 7)):  # a few strokes each
            if rng.randint(2):
                r = rng.randint(2, side - 2)
                a, b = sorted(rng.randint(0, side, 2))
                glyphs[c, r, a:b + 1] = 1.0
            else:
                col = rng.randint(2, side - 2)
                a, b = sorted(rng.randint(0, side, 2))
                glyphs[c, a:b + 1, col] = 1.0
    return glyphs


class KanjiLoader(FullBatchLoaderMSE):
    """Noisy jittered glyph instances → clean glyph targets."""

    MAPPING = "kanji_loader"

    def __init__(self, workflow, **kwargs):
        self.n_classes = kwargs.pop("n_classes", 16)
        self.n_train = kwargs.pop("n_train", 800)
        self.n_valid = kwargs.pop("n_valid", 200)
        super().__init__(workflow, **kwargs)

    def load_data(self):
        glyphs = make_glyphs(self.n_classes)
        rng = numpy.random.RandomState(54)

        def make(n):
            labels = rng.randint(0, self.n_classes, n)
            data = glyphs[labels].copy()
            for i in range(n):
                data[i] = numpy.roll(
                    numpy.roll(data[i], rng.randint(-2, 3), 0),
                    rng.randint(-2, 3), 1)
            data += rng.normal(0, 0.25, data.shape)
            return (numpy.clip(data, 0, 1.5).reshape(n, -1),
                    glyphs[labels].reshape(n, -1), labels)

        vd, vt, vl = make(self.n_valid)
        td, tt, tl = make(self.n_train)
        self.original_data.mem = numpy.concatenate([vd, td])
        self.original_targets.mem = numpy.concatenate([vt, tt])
        self.original_labels = list(numpy.concatenate([vl, tl]))
        self.class_lengths[TEST] = 0
        self.class_lengths[VALID] = self.n_valid
        self.class_lengths[TRAIN] = self.n_train


def create_workflow(fused=True, **overrides):
    from . import build_standard
    return build_standard(root.kanji, "Kanji", KanjiLoader, "mse",
                          fused=fused, **overrides)


def run(load, main):
    load(create_workflow)
    main()
