"""Sample model workflows (the Znicz samples inventory — SURVEY.md §2.9:
MNIST, MnistSimple, MnistAE, CIFAR10, AlexNet, STL10, Kohonen...)."""


def build_standard(cfg, name, default_loader_factory, loss_function,
                   **overrides):
    """Shared config-merge for the StandardWorkflow samples: defaults
    from the sample's config namespace, overridden per call."""
    from ..standard_workflow import StandardWorkflow
    decision = cfg.decision.todict()
    decision.update(overrides.pop("decision", {}))
    loader = cfg.loader.todict()
    loader.update(overrides.pop("loader", {}))
    layers = overrides.pop("layers", cfg.layers)
    if "snapshotter" in cfg and "snapshotter" not in overrides:
        overrides["snapshotter"] = cfg.snapshotter.todict()
    return StandardWorkflow(
        None, name=name,
        loader_factory=overrides.pop("loader_factory",
                                     default_loader_factory),
        loader=loader, layers=layers, loss_function=loss_function,
        decision=decision, **overrides)
