"""Sample model workflows (the Znicz samples inventory — SURVEY.md §2.9:
MNIST, MnistSimple, MnistAE, CIFAR10, AlexNet, Kohonen, Lines...)."""
