"""Sample model workflows (the Znicz samples inventory — SURVEY.md §2.9:
MNIST, MnistSimple, MnistAE, CIFAR10, AlexNet, STL10, Kohonen...)."""


def build_standard(cfg, name, default_loader_factory, loss_function,
                   **overrides):
    """Shared config-merge for the StandardWorkflow samples: defaults
    from the sample's config namespace, overridden per call.  Topology
    comes from ``layers`` OR the ``mcdnnic_topology`` string (with
    ``mcdnnic_parameters``), whichever the config/overrides provide."""
    from ..standard_workflow import StandardWorkflow
    from ...config import Config

    def _cfg_dict(v):
        # config files may ASSIGN a plain dict (root.x.decision =
        # {...}) instead of update()-ing into the tree — accept both
        return v.todict() if isinstance(v, Config) else dict(v)

    decision = _cfg_dict(cfg.decision)
    decision.update(overrides.pop("decision", {}))
    loader = _cfg_dict(cfg.loader)
    loader.update(overrides.pop("loader", {}))
    topology = {}
    mcdnnic = overrides.pop("mcdnnic_topology",
                            cfg.get("mcdnnic_topology"))
    if "layers" in overrides:
        topology["layers"] = overrides.pop("layers")
        overrides.pop("mcdnnic_parameters", None)
    elif mcdnnic:
        params = overrides.pop("mcdnnic_parameters",
                               cfg.get("mcdnnic_parameters"))
        if params is not None:
            params = _cfg_dict(params)
        topology = {"mcdnnic_topology": mcdnnic,
                    "mcdnnic_parameters": params}
    else:
        topology["layers"] = cfg.layers
    if "snapshotter" in cfg and "snapshotter" not in overrides:
        overrides["snapshotter"] = _cfg_dict(cfg.snapshotter)
    return StandardWorkflow(
        None, name=name,
        loader_factory=overrides.pop("loader_factory",
                                     default_loader_factory),
        loader=loader, loss_function=loss_function,
        decision=decision, **topology, **overrides)
