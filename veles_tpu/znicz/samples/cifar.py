"""CIFAR-10 convnet sample.

Re-creation of the Znicz CIFAR10 caffe-config sample (absent submodule;
published baseline 17.21 % validation error,
/root/reference/docs/source/manualrst_veles_algorithms.rst:50).  Topology
follows the caffe CIFAR quick net: 3x(conv→pool) → fc → softmax.

Real CIFAR-10 python batches are loaded when present under
``root.common.dirs.datasets/cifar-10-batches-py``; otherwise a
deterministic synthetic twin with identical shapes is used (zero-egress
build environment).
"""

import os
import pickle

import numpy

from ...config import root
from ...loader.fullbatch import FullBatchLoader
from ...loader.base import TEST, VALID, TRAIN
from ..standard_workflow import StandardWorkflow

root.cifar.update({
    "loader": {"minibatch_size": 100,
               "normalization_type": "internal_mean",
               "normalization_parameters": {"scale": 1.0 / 128}},
    "layers": [
        {"type": "conv", "->": {"n_kernels": 32, "kx": 5, "ky": 5,
                                "padding": 2, "weights_stddev": 0.0001},
         "<-": {"learning_rate": 0.001, "gradient_moment": 0.9,
                "weights_decay": 0.004}},
        {"type": "max_pooling", "->": {"kx": 3, "ky": 3,
                                       "sliding": (2, 2)}},
        {"type": "activation_str"},
        {"type": "conv_str", "->": {"n_kernels": 32, "kx": 5, "ky": 5,
                                    "padding": 2, "weights_stddev": 0.01},
         "<-": {"learning_rate": 0.001, "gradient_moment": 0.9,
                "weights_decay": 0.004}},
        {"type": "avg_pooling", "->": {"kx": 3, "ky": 3,
                                       "sliding": (2, 2)}},
        {"type": "conv_str", "->": {"n_kernels": 64, "kx": 5, "ky": 5,
                                    "padding": 2, "weights_stddev": 0.01},
         "<-": {"learning_rate": 0.001, "gradient_moment": 0.9,
                "weights_decay": 0.004}},
        {"type": "avg_pooling", "->": {"kx": 3, "ky": 3,
                                       "sliding": (2, 2)}},
        {"type": "all2all", "->": {"output_sample_shape": 64,
                                   "weights_stddev": 0.1},
         "<-": {"learning_rate": 0.001, "gradient_moment": 0.9,
                "weights_decay": 0.004}},
        {"type": "softmax", "->": {"output_sample_shape": 10,
                                   "weights_stddev": 0.1},
         "<-": {"learning_rate": 0.001, "gradient_moment": 0.9,
                "weights_decay": 1.0}},
    ],
    "decision": {"max_epochs": 60, "fail_iterations": 100},
})


def _synthetic_cifar(n_train, n_valid, seed=977):
    """Deterministic CIFAR-shaped 10-class problem (32x32x3 uint8)."""
    rng = numpy.random.RandomState(seed)
    templates = rng.uniform(0, 1, (10, 8, 8, 3))
    temps = numpy.kron(templates, numpy.ones((1, 4, 4, 1)))

    def make(n, rs):
        labels = rs.randint(0, 10, n)
        imgs = temps[labels]
        imgs = imgs + rs.normal(0, 0.25, imgs.shape)
        rolls = rs.randint(-3, 4, (n, 2))
        for i in range(n):
            imgs[i] = numpy.roll(imgs[i], tuple(rolls[i]), (0, 1))
        return (numpy.clip(imgs, 0, 1.3) / 1.3 * 255).astype(numpy.uint8), \
            labels.astype(numpy.int32)

    return (make(n_train, numpy.random.RandomState(seed + 1)),
            make(n_valid, numpy.random.RandomState(seed + 2)))


class CifarLoader(FullBatchLoader):
    MAPPING = "cifar_loader"

    def __init__(self, workflow, **kwargs):
        self.n_train = kwargs.pop("n_train", None)
        self.n_valid = kwargs.pop("n_valid", None)
        #: "real" when the on-disk CIFAR-10 batches were used,
        #: "synthetic" for the twin (same contract as the MNIST loader)
        self.provenance = None
        super().__init__(workflow, **kwargs)

    def load_data(self):
        d = os.path.join(os.path.expanduser(
            root.common.dirs.get("datasets", "")), "cifar-10-batches-py")
        if os.path.isdir(d):
            self.provenance = "real"
            imgs, labels = [], []
            for name in ["data_batch_%d" % i for i in range(1, 6)]:
                with open(os.path.join(d, name), "rb") as f:
                    batch = pickle.load(f, encoding="bytes")
                imgs.append(batch[b"data"])
                labels += list(batch[b"labels"])
            ti = numpy.concatenate(imgs).reshape(-1, 3, 32, 32).transpose(
                0, 2, 3, 1)
            tl = numpy.array(labels, numpy.int32)
            with open(os.path.join(d, "test_batch"), "rb") as f:
                batch = pickle.load(f, encoding="bytes")
            vi = batch[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
            vl = numpy.array(batch[b"labels"], numpy.int32)
            ti, tl = ti[:self.n_train], tl[:self.n_train]
            vi, vl = vi[:self.n_valid], vl[:self.n_valid]
        else:
            self.provenance = "synthetic"
            (ti, tl), (vi, vl) = _synthetic_cifar(
                self.n_train or 5000, self.n_valid or 1000)
        data = numpy.concatenate([vi, ti]).astype(numpy.float32)
        self.original_data.mem = data
        self.original_labels = list(numpy.concatenate([vl, tl]))
        self.class_lengths[TEST] = 0
        self.class_lengths[VALID] = len(vi)
        self.class_lengths[TRAIN] = len(ti)


def create_workflow(fused=True, **overrides):
    from . import build_standard
    return build_standard(root.cifar, "CifarConvnet", CifarLoader, "softmax",
                          fused=fused, **overrides)

def run(load, main):
    load(create_workflow)
    main()
