"""MnistAE: fully-connected MNIST autoencoder (BASELINE gate model).

Re-creation of the Znicz MnistAE sample (absent submodule; published
baseline — 0.5478 validation RMSE — from
/root/reference/docs/source/manualrst_veles_algorithms.rst:55-69).

Topology: 784 → tanh(100) → linear(784), trained with MSE against the
input image itself (targets = data).  Rides the same MSE stack the
regression workflows use: FullBatchLoaderMSE serves (data, targets) pairs
resident in HBM, the fused step computes the 0.5·sum-squared-error loss,
and DecisionMSE tracks per-epoch RMSE with early stopping.
"""

import numpy

from ...config import root
from ...loader.fullbatch import FullBatchLoaderMSE
from ...loader.base import TEST, VALID, TRAIN
from ...datasets import load_digits_idx
from ..standard_workflow import StandardWorkflow

root.mnist_ae.update({
    "loader": {"minibatch_size": 100,
               "normalization_type": "range_linear",
               "target_normalization_type": "range_linear"},
    "layers": [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 100,
                                        "weights_stddev": 0.05},
         "<-": {"learning_rate": 0.01, "weights_decay": 0.0,
                "gradient_moment": 0.9}},
        {"type": "all2all", "->": {"output_sample_shape": 784,
                                   "weights_stddev": 0.05},
         "<-": {"learning_rate": 0.01, "weights_decay": 0.0,
                "gradient_moment": 0.9}},
    ],
    "decision": {"max_epochs": 20, "fail_iterations": 20},
})


class MnistAELoader(FullBatchLoaderMSE):
    """MNIST with the images doubling as regression targets."""

    MAPPING = "mnist_ae_loader"

    def __init__(self, workflow, **kwargs):
        self.n_train = kwargs.pop("n_train", None)
        self.n_valid = kwargs.pop("n_valid", None)
        self.use_fixture = kwargs.pop("use_fixture", True)
        super().__init__(workflow, **kwargs)

    def load_data(self):
        (ti, tl), (vi, vl), self.provenance = load_digits_idx(
            self.n_train, self.n_valid, fixture=self.use_fixture)
        self.is_real = self.provenance == "real"
        data = numpy.concatenate([vi, ti]).astype(numpy.float32)
        data = data.reshape(len(data), -1)
        self.original_data.mem = data
        self.original_targets.mem = data.copy()
        self.class_lengths[TEST] = 0
        self.class_lengths[VALID] = len(vi)
        self.class_lengths[TRAIN] = len(ti)


def create_workflow(fused=True, **overrides):
    from . import build_standard
    return build_standard(root.mnist_ae, "MnistAE", MnistAELoader, "mse",
                          fused=fused, **overrides)

def run(load, main):
    load(create_workflow)
    main()
