"""DemoKohonen: 2-D point clusters self-organized onto an 8x8 map.

Re-creation of the Znicz DemoKohonen sample (absent submodule; listed in
/root/reference/docs/source/manualrst_veles_algorithms.rst:85 and
BASELINE.json config #5).  A synthetic 2-D Gaussian-cluster dataset is
mapped by a KohonenTrainer (online SOM, jitted scan — znicz/kohonen.py);
the quantization error drops as the codebook unfolds over the data.
"""

import numpy

from ...config import root
from ...loader.fullbatch import FullBatchLoader
from ...loader.base import TEST, VALID, TRAIN
from ...workflow import Workflow
from ...plumbing import Repeater
from ..kohonen import KohonenTrainer, KohonenDecision

root.kohonen.update({
    "loader": {"minibatch_size": 50, "normalization_type": "none"},
    "trainer": {"shape": (8, 8), "learning_rate": 0.5,
                "learning_rate_final": 0.05},
    "decision": {"max_epochs": 30},
})


class KohonenLoader(FullBatchLoader):
    """Synthetic 2-D clusters (train-only, unlabeled)."""

    MAPPING = "kohonen_demo_loader"

    def __init__(self, workflow, **kwargs):
        self.n_train = kwargs.pop("n_train", 1000)
        self.n_clusters = kwargs.pop("n_clusters", 4)
        super().__init__(workflow, **kwargs)
        self.has_labels = False

    def load_data(self):
        rng = numpy.random.RandomState(7)
        centers = rng.uniform(-2.0, 2.0, (self.n_clusters, 2))
        per = self.n_train // self.n_clusters
        chunks = [centers[i] + 0.25 * rng.randn(per, 2)
                  for i in range(self.n_clusters)]
        data = numpy.concatenate(chunks).astype(numpy.float32)
        rng.shuffle(data)
        self.original_data.mem = data
        self.class_lengths[TEST] = 0
        self.class_lengths[VALID] = 0
        self.class_lengths[TRAIN] = len(data)


class KohonenWorkflow(Workflow):
    """repeater → loader → trainer → decision → loop (no-grad path)."""

    def __init__(self, launcher, **kwargs):
        super().__init__(launcher, name=kwargs.pop("name", "DemoKohonen"))
        loader_cfg = dict(root.kohonen.loader.todict())
        loader_cfg.update(kwargs.pop("loader", {}))
        trainer_cfg = dict(root.kohonen.trainer.todict())
        trainer_cfg.update(kwargs.pop("trainer", {}))
        decision_cfg = dict(root.kohonen.decision.todict())
        decision_cfg.update(kwargs.pop("decision", {}))
        trainer_cfg.setdefault("epochs", decision_cfg.get("max_epochs", 30))

        self.repeater = Repeater(self)
        self.repeater.link_from(self.start_point)
        self.loader = KohonenLoader(self, **loader_cfg)
        self.loader.link_from(self.repeater)
        self.trainer = KohonenTrainer(self, **trainer_cfg)
        self.trainer.link_from(self.loader)
        self.trainer.link_loader(self.loader)
        self.decision = KohonenDecision(self, **decision_cfg)
        self.decision.link_from(self.trainer)
        self.decision.link_loader(self.loader)
        self.decision.link_trainer(self.trainer)
        self.repeater.link_from(self.decision)
        self.end_point.link_from(self.decision)
        self.repeater.gate_block = self.decision.complete
        self.end_point.gate_block = ~self.decision.complete


def create_workflow(**overrides):
    return KohonenWorkflow(None, **overrides)


def run(load, main):
    load(create_workflow)
    main()
