"""VideoAE: convolutional autoencoder over video frames.

Re-creation of the Znicz VideoAE sample (absent submodule; named in the
reference's sample inventory, SURVEY.md §2.9) — the conv-autoencoder
demo: conv → pool encode, depool → deconv decode, MSE against the input
frame.  This is the sample that exercises the deconv/depooling pair
end-to-end (misc_units.Deconv/Depooling).

Real video decoding is environment-gated; the loader synthesizes a
deterministic "video": frames of a square sprite orbiting a 32x32 field
with additive noise — an actual temporal structure the AE must compress.
Drop frames extracted from a real clip into the same loader via
``frames=`` to reproduce the reference demo faithfully.
"""

import numpy

from ...config import root
from ...loader.fullbatch import FullBatchLoaderMSE
from ...loader.base import TEST, VALID, TRAIN

_LR = {"learning_rate": 3e-5, "gradient_moment": 0.9}

root.video_ae.update({
    "loader": {"minibatch_size": 50,
               "normalization_type": "range_linear",
               "target_normalization_type": "range_linear"},
    "layers": [
        {"type": "conv_tanh", "->": {"n_kernels": 8, "kx": 5, "ky": 5,
                                     "padding": 2,
                                     "weights_stddev": 0.05}, "<-": _LR},
        {"type": "max_pooling", "->": {"kx": 2, "ky": 2,
                                       "sliding": (2, 2)}},
        {"type": "depooling", "->": {"kx": 2, "ky": 2}},
        {"type": "deconv", "->": {"n_kernels": 1, "kx": 5, "ky": 5,
                                  "padding": 2, "weights_stddev": 0.05},
         "<-": _LR},
    ],
    "decision": {"max_epochs": 15, "fail_iterations": 20},
})


def synthetic_video(n_frames, side=32, seed=31):
    """A sprite orbiting the frame + noise; (n, side, side, 1) float32."""
    rng = numpy.random.RandomState(seed)
    frames = numpy.zeros((n_frames, side, side, 1), numpy.float32)
    for t in range(n_frames):
        angle = 2 * numpy.pi * t / 24.0
        cy = int(side / 2 + (side / 3) * numpy.sin(angle))
        cx = int(side / 2 + (side / 3) * numpy.cos(angle))
        y0, x0 = max(cy - 3, 0), max(cx - 3, 0)
        frames[t, y0:cy + 3, x0:cx + 3, 0] = 1.0
        frames[t, :, :, 0] += rng.normal(0, 0.05, (side, side))
    return numpy.clip(frames, 0.0, 1.0)


class VideoFramesLoader(FullBatchLoaderMSE):
    """Frames double as their own MSE targets (autoencoder)."""

    MAPPING = "video_ae_loader"

    def __init__(self, workflow, **kwargs):
        self.n_train = kwargs.pop("n_train", 480)
        self.n_valid = kwargs.pop("n_valid", 120)
        self.frames = kwargs.pop("frames", None)
        super().__init__(workflow, **kwargs)

    def load_data(self):
        if self.frames is not None:
            frames = numpy.asarray(self.frames, numpy.float32)
            n_valid = min(self.n_valid, len(frames) // 5)
        else:
            frames = synthetic_video(self.n_train + self.n_valid)
            n_valid = self.n_valid
        data = frames.astype(numpy.float32)
        self.original_data.mem = data
        self.original_targets.mem = data.copy()
        self.class_lengths[TEST] = 0
        self.class_lengths[VALID] = n_valid
        self.class_lengths[TRAIN] = len(data) - n_valid


def create_workflow(fused=True, **overrides):
    from . import build_standard
    return build_standard(root.video_ae, "VideoAE", VideoFramesLoader,
                          "mse", fused=fused, **overrides)


def run(load, main):
    load(create_workflow)
    main()
