"""ImageNet AlexNet sample — the BASELINE.json headline workflow.

Re-creation of the Znicz AlexNet (absent submodule; model status
/root/reference/docs/source/manualrst_veles_algorithms.rst:56-63).
Canonical single-tower AlexNet (the two-GPU grouping of the 2012 paper is
an artifact of 3GB GPUs; on TPU the MXU wants the full-width convs, and
the Znicz ZeroFiller grouping trick remains available via the
``zero_filler`` unit for strict parity experiments):

conv11x11/4x96 → LRN → max3x3/2 → conv5x5x256 → LRN → max3x3/2 →
conv3x3x384 → conv3x3x384 → conv3x3x256 → max3x3/2 → fc4096 → dropout →
fc4096 → dropout → softmax1000

Input: 227x227x3.  Real ImageNet is not distributable with the repo; the
loader serves deterministic synthetic ImageNet-shaped data (the bench
measures throughput; accuracy parity runs require user-supplied data, as
with the reference).
"""

import numpy

from ...config import root
from ...loader.fullbatch import FullBatchLoader
from ...loader.base import TEST, VALID, TRAIN
from ..standard_workflow import StandardWorkflow

_LR = {"learning_rate": 0.01, "gradient_moment": 0.9,
       "weights_decay": 0.0005}

root.alexnet.update({
    "loader": {"minibatch_size": 128, "normalization_type": "none"},
    "layers": [
        {"type": "conv_str", "->": {"n_kernels": 96, "kx": 11, "ky": 11,
                                    "sliding": (4, 4),
                                    "weights_stddev": 0.01}, "<-": _LR},
        {"type": "norm", "->": {"alpha": 1e-4, "beta": 0.75, "n": 5,
                                "k": 2.0}},
        {"type": "max_pooling", "->": {"kx": 3, "ky": 3,
                                       "sliding": (2, 2)}},
        {"type": "conv_str", "->": {"n_kernels": 256, "kx": 5, "ky": 5,
                                    "padding": 2,
                                    "weights_stddev": 0.01}, "<-": _LR},
        {"type": "norm", "->": {"alpha": 1e-4, "beta": 0.75, "n": 5,
                                "k": 2.0}},
        {"type": "max_pooling", "->": {"kx": 3, "ky": 3,
                                       "sliding": (2, 2)}},
        {"type": "conv_str", "->": {"n_kernels": 384, "kx": 3, "ky": 3,
                                    "padding": 1,
                                    "weights_stddev": 0.01}, "<-": _LR},
        {"type": "conv_str", "->": {"n_kernels": 384, "kx": 3, "ky": 3,
                                    "padding": 1,
                                    "weights_stddev": 0.01}, "<-": _LR},
        {"type": "conv_str", "->": {"n_kernels": 256, "kx": 3, "ky": 3,
                                    "padding": 1,
                                    "weights_stddev": 0.01}, "<-": _LR},
        {"type": "max_pooling", "->": {"kx": 3, "ky": 3,
                                       "sliding": (2, 2)}},
        {"type": "all2all_str", "->": {"output_sample_shape": 4096,
                                       "weights_stddev": 0.005},
         "<-": _LR},
        {"type": "dropout", "->": {"dropout_ratio": 0.5}},
        {"type": "all2all_str", "->": {"output_sample_shape": 4096,
                                       "weights_stddev": 0.005},
         "<-": _LR},
        {"type": "dropout", "->": {"dropout_ratio": 0.5}},
        {"type": "softmax", "->": {"output_sample_shape": 1000,
                                   "weights_stddev": 0.01}, "<-": _LR},
    ],
    "decision": {"max_epochs": 90, "fail_iterations": 1000},
})


class SyntheticImagenetLoader(FullBatchLoader):
    """Deterministic ImageNet-shaped data resident in HBM (bench)."""

    MAPPING = "synthetic_imagenet_loader"

    def __init__(self, workflow, **kwargs):
        self.n_train = kwargs.pop("n_train", 2048)
        self.n_valid = kwargs.pop("n_valid", 256)
        self.n_classes = kwargs.pop("n_classes", 1000)
        self.side = kwargs.pop("side", 227)
        super().__init__(workflow, **kwargs)

    def load_data(self):
        rng = numpy.random.RandomState(11)
        n = self.n_train + self.n_valid
        self.original_data.mem = rng.uniform(
            -0.5, 0.5, (n, self.side, self.side, 3)).astype(numpy.float32)
        self.original_labels = list(
            rng.randint(0, self.n_classes, n).astype(numpy.int32))
        self.class_lengths[TEST] = 0
        self.class_lengths[VALID] = self.n_valid
        self.class_lengths[TRAIN] = self.n_train


def create_workflow(fused=True, **overrides):
    from . import build_standard
    return build_standard(root.alexnet, "AlexNet", SyntheticImagenetLoader, "softmax",
                          fused=fused, **overrides)

def run(load, main):
    load(create_workflow)
    main()
