"""Lines: orientation classification, topology via mcdnnic notation.

Re-creation of the Znicz Lines sample (absent submodule): the reference's
documented user of ``mcdnnic_topology``
(/root/reference/docs/source/manualrst_veles_workflow_creation.rst:41-47
points at veles.znicz.samples.Lines.lines) — a small convnet classifying
images of straight lines by orientation, with the whole topology given
as one MCDNN string and per-layer defaults via ``mcdnnic_parameters``.

The reference trained on downloaded line photos; the loader here draws
deterministic synthetic lines in 4 orientations (horizontal, vertical,
the two diagonals) with noise and jitter — same task shape, zero egress.
"""

import numpy

from ...config import root
from ...loader.fullbatch import FullBatchLoader
from ...loader.base import TEST, VALID, TRAIN

root.lines.update({
    "loader": {"minibatch_size": 40, "normalization_type": "mean_disp"},
    "mcdnnic_topology": "1x32x32-8C5-MP2-16C5-MP2-64N-4N",
    "mcdnnic_parameters": {
        "->": {"weights_stddev": 0.1},
        "<-": {"learning_rate": 0.05, "gradient_moment": 0.9},
    },
    "decision": {"max_epochs": 10, "fail_iterations": 20},
})


def draw_line(orientation, side=32, rng=None):
    img = numpy.zeros((side, side, 1), numpy.float32)
    off = rng.randint(-side // 4, side // 4 + 1) if rng is not None else 0
    idx = numpy.arange(side)
    if orientation == 0:      # horizontal
        img[numpy.clip(side // 2 + off, 0, side - 1), :, 0] = 1.0
    elif orientation == 1:    # vertical
        img[:, numpy.clip(side // 2 + off, 0, side - 1), 0] = 1.0
    elif orientation == 2:    # main diagonal
        img[idx, numpy.clip(idx + off, 0, side - 1), 0] = 1.0
    else:                     # anti-diagonal
        img[idx, numpy.clip(side - 1 - idx + off, 0, side - 1), 0] = 1.0
    if rng is not None:
        img[:, :, 0] += rng.normal(0, 0.1, (side, side))
    return img


class LinesLoader(FullBatchLoader):
    MAPPING = "lines_loader"

    def __init__(self, workflow, **kwargs):
        self.n_train = kwargs.pop("n_train", 400)
        self.n_valid = kwargs.pop("n_valid", 100)
        super().__init__(workflow, **kwargs)

    def load_data(self):
        rng = numpy.random.RandomState(17)
        data, labels = [], []
        for i in range(self.n_valid + self.n_train):
            orientation = i % 4
            data.append(draw_line(orientation, rng=rng))
            labels.append(orientation)
        self.original_data.mem = numpy.stack(data)
        self.original_labels = labels
        self.class_lengths[TEST] = 0
        self.class_lengths[VALID] = self.n_valid
        self.class_lengths[TRAIN] = self.n_train


def create_workflow(fused=True, **overrides):
    from . import build_standard
    return build_standard(root.lines, "Lines", LinesLoader, "softmax",
                          fused=fused, **overrides)


def run(load, main):
    load(create_workflow)
    main()
