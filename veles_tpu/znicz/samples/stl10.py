"""STL-10 convnet sample.

Re-creation of the Znicz STL-10 sample (absent submodule; published
baseline 35.10 % validation error,
/root/reference/docs/source/manualrst_veles_algorithms.rst:51).
STL-10: 96x96x3 images, 10 classes, small labeled set (5k train /
8k test) — the same caffe-quick-style conv stack as CIFAR, scaled for
the larger input with a third pooling stage.

Real STL-10 binary files are loaded when present under
``root.common.dirs.datasets/stl10_binary`` (``train_X.bin`` etc.);
otherwise a deterministic synthetic twin with identical shapes is used
(zero-egress build environment).
"""

import os

import numpy

from ...config import root
from ...loader.fullbatch import FullBatchLoader
from ...loader.base import TEST, VALID, TRAIN
from ..standard_workflow import StandardWorkflow

_LR = {"learning_rate": 0.01, "gradient_moment": 0.9,
       "weights_decay": 0.004}

root.stl10.update({
    "loader": {"minibatch_size": 50,
               "normalization_type": "range_linear"},
    "layers": [
        {"type": "conv_str", "->": {"n_kernels": 32, "kx": 5, "ky": 5,
                                    "padding": 2,
                                    "weights_stddev": 0.05}, "<-": _LR},
        {"type": "max_pooling", "->": {"kx": 3, "ky": 3,
                                       "sliding": (2, 2)}},
        {"type": "conv_str", "->": {"n_kernels": 32, "kx": 5, "ky": 5,
                                    "padding": 2,
                                    "weights_stddev": 0.05}, "<-": _LR},
        {"type": "avg_pooling", "->": {"kx": 3, "ky": 3,
                                       "sliding": (2, 2)}},
        {"type": "conv_str", "->": {"n_kernels": 64, "kx": 5, "ky": 5,
                                    "padding": 2,
                                    "weights_stddev": 0.05}, "<-": _LR},
        {"type": "avg_pooling", "->": {"kx": 3, "ky": 3,
                                       "sliding": (2, 2)}},
        {"type": "all2all", "->": {"output_sample_shape": 128,
                                   "weights_stddev": 0.05}, "<-": _LR},
        {"type": "softmax", "->": {"output_sample_shape": 10,
                                   "weights_stddev": 0.05}, "<-": _LR},
    ],
    "decision": {"max_epochs": 100, "fail_iterations": 20},
})


def _synthetic_stl10(n_train, n_valid, seed=1453):
    """Deterministic class-structured synthetic twin (96x96x3)."""
    rng = numpy.random.RandomState(seed)
    protos = rng.uniform(-0.6, 0.6, (10, 12, 12, 3)).astype(numpy.float32)

    def make(n):
        labels = rng.randint(0, 10, n).astype(numpy.int32)
        base = protos[labels]
        up = numpy.repeat(numpy.repeat(base, 8, axis=1), 8, axis=2)
        data = up + rng.normal(0, 0.25, up.shape).astype(numpy.float32)
        return (data * 128 + 128).clip(0, 255).astype(numpy.float32), \
            labels
    return make(n_train), make(n_valid)


class Stl10Loader(FullBatchLoader):
    """STL-10 binary files when present, synthetic twin otherwise."""

    MAPPING = "stl10_loader"

    def __init__(self, workflow, **kwargs):
        self.n_train = kwargs.pop("n_train", None)
        self.n_valid = kwargs.pop("n_valid", None)
        super().__init__(workflow, **kwargs)

    def load_data(self):
        d = os.path.join(root.common.dirs.get("datasets", "."),
                         "stl10_binary")

        def read_split(xname, yname):
            with open(os.path.join(d, xname), "rb") as f:
                x = numpy.frombuffer(f.read(), numpy.uint8)
            # column-major 96x96 per channel (STL-10 binary layout)
            x = x.reshape(-1, 3, 96, 96).transpose(0, 3, 2, 1)
            with open(os.path.join(d, yname), "rb") as f:
                y = numpy.frombuffer(f.read(), numpy.uint8).astype(
                    numpy.int32) - 1
            return x.astype(numpy.float32), y

        if os.path.exists(os.path.join(d, "train_X.bin")):
            ti, tl = read_split("train_X.bin", "train_y.bin")
            vi, vl = read_split("test_X.bin", "test_y.bin")
            if self.n_train:
                ti, tl = ti[:self.n_train], tl[:self.n_train]
            if self.n_valid:
                vi, vl = vi[:self.n_valid], vl[:self.n_valid]
        else:
            (ti, tl), (vi, vl) = _synthetic_stl10(
                self.n_train or 5000, self.n_valid or 800)
        self.original_data.mem = numpy.concatenate([vi, ti])
        self.original_labels = list(numpy.concatenate([vl, tl]))
        self.class_lengths[TEST] = 0
        self.class_lengths[VALID] = len(vi)
        self.class_lengths[TRAIN] = len(ti)


def create_workflow(fused=True, **overrides):
    from . import build_standard
    return build_standard(root.stl10, "Stl10Convnet", Stl10Loader, "softmax",
                          fused=fused, **overrides)

def run(load, main):
    load(create_workflow)
    main()
