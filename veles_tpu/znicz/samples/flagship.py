"""Flagship composition demo: a modern MoE transformer trained over a
dp x pp x ep mesh.

The "modern demo" SURVEY §5 contemplates (VERDICT round-3 item 10): the
round-3/4 parallel primitives composed in ONE model —

- each block = causal multi-head attention (the functional core of
  ``znicz.attention`` / ``parallel.ring.attention_reference``) + an
  RMS-norm + a **switch-MoE feed-forward** whose experts shard over the
  ``expert`` mesh axis (``parallel.moe._moe_local``);
- a stack of S identical blocks pipelined over the ``pipe`` axis with
  the GPipe microbatch schedule (``parallel.pipeline._gpipe_local``);
- the batch sharded over ``data``;
- optionally the SEQUENCE sharded over ``seq``: pass ``seq_axis`` and
  the attention inside every pipelined block becomes ring attention
  (``parallel.ring._ring_attention_local``) — K/V chunks ride
  ppermutes over the seq ring while activations ride the pipe ring.

Up to FOUR mesh axes live in ONE ``shard_map`` program: the pipeline
ring ppermutes over ``pipe``, the attention ring over ``seq``, the MoE
combine psums over ``expert``, and XLA inserts the gradient all-reduce
over ``data`` — the full quintet minus tp, which composes the same way
(tensor sharding annotates the projections).

``flagship_reference`` is the single-device oracle (sequential blocks,
oracle MoE); the test asserts forward parity AND that one fused train
step on the dp2 x pp2 x ep2 8-device mesh learns
(tests/test_flagship.py).
"""

import functools
import math

import jax
import jax.numpy as jnp
import numpy

from ...parallel.mesh import make_mesh
from ...parallel.moe import _moe_local, moe_capacity, moe_reference
from ...parallel.pipeline import _gpipe_local
from ...parallel.ring import _ring_attention_local, attention_reference


def init_params(stages, experts, d=16, heads=2, hidden=32, seed=0):
    """One stacked param tree: leading dim S (pipe), expert leaves
    [S, E, ...]."""
    rng = numpy.random.RandomState(seed)

    def w(*shape, scale=0.25):
        return jnp.asarray(rng.standard_normal(shape) * scale,
                           jnp.float32)

    return {
        "qkv": w(stages, d, 3 * d),
        "proj": w(stages, d, d),
        "wr": w(stages, d, experts),
        "w1": w(stages, experts, d, hidden),
        "w2": w(stages, experts, hidden, d),
    }


def _rmsnorm(h):
    return h * jax.lax.rsqrt(jnp.mean(h * h, axis=-1, keepdims=True) +
                             1e-6)


def _expert_ffn(p, h):
    return jnp.maximum(h @ p["w1"], 0.0) @ p["w2"]


def _attend_block(params, h, heads, seq_axis=None, vary_axes=None,
                  use_pallas=False):
    b, t, d = h.shape
    qkv = _rmsnorm(h) @ params["qkv"]
    q, k, v = (qkv[..., i * d:(i + 1) * d].reshape(b, t, heads,
                                                   d // heads)
               for i in range(3))
    if seq_axis is None:
        a = attention_reference(q, k, v, causal=True)
    else:
        # inside the full-mesh shard_map: t is this shard's chunk and
        # the K/V blocks ride the seq ring (flash recurrence);
        # use_pallas swaps in ring FLASH attention (per-hop Pallas
        # kernels, parallel/ring.py) when the chunk tiles
        local = _ring_attention_local
        if use_pallas:
            from ...parallel.ring import _ring_flash_local
            from ..flash_attention import flash_attention_supported
            if flash_attention_supported(t):
                local = _ring_flash_local
        a = local(
            q, k, v, axis_name=seq_axis, causal=True,
            scale=1.0 / math.sqrt(d // heads), vary_axes=vary_axes)
    return h + a.reshape(b, t, d) @ params["proj"]


def _block_sharded(params, h, *, heads, capacity, k, seq_axis=None,
                   vary_axes=None, use_pallas=False):
    """One transformer block INSIDE the full-mesh shard_map: expert
    leaves carry a leading local-expert dim (1), the MoE dispatch
    psums over the bound ``expert`` axis, and (when ``seq_axis`` is
    bound) attention rides the seq ring."""
    h = _attend_block(params, h, heads, seq_axis=seq_axis,
                      vary_axes=vary_axes, use_pallas=use_pallas)
    b, t, d = h.shape
    flat = _rmsnorm(h).reshape(b * t, d)
    moe = _moe_local({"w1": params["w1"], "w2": params["w2"]},
                     params["wr"], flat, expert_apply=_expert_ffn,
                     capacity=capacity, axis_name="expert", k=k)
    return h + moe.reshape(b, t, d)


def _block_oracle(params, h, *, heads, capacity, k, seq_shards=1):
    """Same block on one device: oracle MoE over the full [E,...]
    stack.  Attention is GLOBAL over T (ring attention equals full
    attention); the MoE queues replay per seq shard, matching the
    sharded path's per-chunk routing."""
    h = _attend_block(params, h, heads)
    b, t, d = h.shape
    normed = _rmsnorm(h)
    outs = []
    for c in range(seq_shards):
        chunk = normed[:, c * (t // seq_shards):
                       (c + 1) * (t // seq_shards)]
        flat = chunk.reshape(-1, d)
        moe = moe_reference(_expert_ffn,
                            {"w1": params["w1"], "w2": params["w2"]},
                            params["wr"], flat, capacity, k=k)
        outs.append(moe.reshape(b, t // seq_shards, d))
    return h + jnp.concatenate(outs, axis=1)


def flagship_apply(params, x, mesh, heads=2, microbatches=None,
                   capacity_factor=2.0, k=1, seq_axis=None,
                   use_pallas=False):
    """The pipelined sharded forward: x [B, T, D] with B over ``data``,
    blocks over ``pipe``, experts over ``expert`` — and T over
    ``seq_axis`` when given (ring attention inside each stage)."""
    from jax.sharding import PartitionSpec as P
    s = mesh.shape["pipe"]
    e = mesh.shape["expert"]
    # the pipeline shard takes p[0] of ITS slice and the MoE shard
    # routes to ITS local experts: stacked params larger than the mesh
    # axes would silently truncate to stage 0 / expert 0 (a 1-device
    # mesh once inflated a bench 4x this way) — fail loudly instead
    got_s = jax.tree_util.tree_leaves(params)[0].shape[0]
    got_e = params["w1"].shape[1]
    if got_s != s or got_e != e:
        raise ValueError(
            "flagship params are stacked for %d stages x %d experts "
            "but the mesh has pipe=%d x expert=%d — sizes must match "
            "(a mismatch would silently run a truncated model)"
            % (got_s, got_e, s, e))
    dp = mesh.shape.get("data", 1)
    sp = mesh.shape.get(seq_axis, 1) if seq_axis else 1
    m = microbatches if microbatches is not None else 2 * s
    b, t, d = x.shape
    tokens_per_mb = (b // dp // m) * (t // sp)
    capacity = moe_capacity(tokens_per_mb, e, capacity_factor, k)
    vary = tuple(a for a in ("data", seq_axis)
                 if a and a in mesh.shape) + ("pipe",)
    block = functools.partial(_block_sharded, heads=heads,
                              capacity=capacity, k=k,
                              seq_axis=seq_axis, vary_axes=vary,
                              use_pallas=use_pallas)
    specs = {"qkv": P("pipe"), "proj": P("pipe"), "wr": P("pipe"),
             "w1": P("pipe", "expert"), "w2": P("pipe", "expert")}
    x_spec = P("data", seq_axis) if seq_axis else P("data")
    fn = jax.shard_map(
        functools.partial(_gpipe_local, block_apply=block, n_stages=s,
                          microbatches=m, axis_name="pipe"),
        mesh=mesh,
        in_specs=({n: specs[n] for n in params}, x_spec),
        out_specs=x_spec)
    return fn(params, x)


def flagship_reference(params, x, heads=2, microbatches=None,
                       capacity_factor=2.0, k=1, data_shards=1,
                       pipe_stages=None, seq_shards=1):
    """Single-device oracle with the SAME capacity semantics: the
    sharded path routes each (data shard, microbatch, seq chunk)
    independently, so the oracle replays that slicing."""
    s = jax.tree_util.tree_leaves(params)[0].shape[0] \
        if pipe_stages is None else pipe_stages
    m = microbatches if microbatches is not None else 2 * s
    b, t, d = x.shape
    tokens_per_mb = (b // data_shards // m) * (t // seq_shards)
    e = params["wr"].shape[-1]
    capacity = moe_capacity(tokens_per_mb, e, capacity_factor, k)
    chunks = x.reshape(data_shards * m, b // data_shards // m, t, d)
    outs = []
    for chunk in chunks:
        h = chunk
        for i in range(s):
            params_i = jax.tree.map(lambda p: p[i], params)
            h = _block_oracle(params_i, h, heads=heads,
                              capacity=capacity, k=k,
                              seq_shards=seq_shards)
        outs.append(h)
    return jnp.concatenate(outs).reshape(b, t, d)


def demo_mesh():
    """The 8-device dp2 x pp2 x ep2 composition mesh (CPU-virtual in
    tests, a pod slice in production)."""
    return make_mesh({"data": 2, "pipe": 2, "expert": 2})


def train_step(params, x, target, mesh, lr=0.05, **kwargs):
    """One fused SGD step of the full composition; jit-able."""
    def loss_fn(p):
        y = flagship_apply(p, x, mesh, **kwargs)
        return ((y - target) ** 2).mean()

    loss, grads = jax.value_and_grad(loss_fn)(params)
    return loss, jax.tree.map(lambda p, g: p - lr * g, params, grads)
