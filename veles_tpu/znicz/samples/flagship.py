"""Flagship composition demo: a modern MoE transformer trained over a
dp x pp x ep mesh.

The "modern demo" SURVEY §5 contemplates (VERDICT round-3 item 10): the
round-3/4 parallel primitives composed in ONE model —

- each block = causal multi-head attention (the functional core of
  ``znicz.attention`` / ``parallel.ring.attention_reference``) + an
  RMS-norm + a **switch-MoE feed-forward** whose experts shard over the
  ``expert`` mesh axis (``parallel.moe._moe_local``);
- a stack of S identical blocks pipelined over the ``pipe`` axis with
  the GPipe microbatch schedule (``parallel.pipeline._gpipe_local``);
- the batch sharded over ``data``;
- optionally the SEQUENCE sharded over ``seq``: pass ``seq_axis`` and
  the attention inside every pipelined block becomes ring attention
  (``parallel.ring._ring_attention_local``) — K/V chunks ride
  ppermutes over the seq ring while activations ride the pipe ring.

Up to FOUR mesh axes live in ONE ``shard_map`` program: the pipeline
ring ppermutes over ``pipe``, the attention ring over ``seq``, the MoE
combine psums over ``expert``, and XLA inserts the gradient all-reduce
over ``data`` — the full quintet minus tp, which composes the same way
(tensor sharding annotates the projections).

``flagship_reference`` is the single-device oracle (sequential blocks,
oracle MoE); the test asserts forward parity AND that one fused train
step on the dp2 x pp2 x ep2 8-device mesh learns
(tests/test_flagship.py).
"""

import functools
import math

import jax
import jax.numpy as jnp
import numpy

from ...parallel.mesh import make_mesh
from ...parallel.moe import _moe_local, moe_capacity, moe_reference
from ...parallel.pipeline import _gpipe_local
from ...parallel.ring import _ring_attention_local, attention_reference


def init_params(stages, experts, d=16, heads=2, hidden=32, seed=0):
    """One stacked param tree: leading dim S (pipe), expert leaves
    [S, E, ...]."""
    rng = numpy.random.RandomState(seed)

    def w(*shape, scale=0.25):
        return jnp.asarray(rng.standard_normal(shape) * scale,
                           jnp.float32)

    return {
        "qkv": w(stages, d, 3 * d),
        "proj": w(stages, d, d),
        "wr": w(stages, d, experts),
        "w1": w(stages, experts, d, hidden),
        "w2": w(stages, experts, hidden, d),
    }


def _rmsnorm(h):
    return h * jax.lax.rsqrt(jnp.mean(h * h, axis=-1, keepdims=True) +
                             1e-6)


def _expert_ffn(p, h):
    return jnp.maximum(h @ p["w1"], 0.0) @ p["w2"]


def _expert_ffn_quant(p, h):
    """The expert FFN over quantized weight leaves: both GEMMs stream
    int8/fp8 weight bytes and fold the per-output-channel scales after
    the K loop (znicz.gemm.quantized_matmul)."""
    from ..gemm import quantized_matmul
    a = jnp.maximum(quantized_matmul(h, p["w1_q"], p["w1_s"]), 0.0)
    return quantized_matmul(a, p["w2_q"], p["w2_s"])


def _quantize_weight_stack(w, dtype):
    """Per-output-channel quantization of a stacked ``[..., K, N]``
    weight (stages x experts leading dims) — the stacked counterpart of
    :func:`~veles_tpu.znicz.gemm.quantize_weight`, sliced per stage and
    per expert by the decode path's existing tree_map indexing."""
    from ..gemm import _FP8_E4M3_MAX, fp8_dtype
    w = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=-2)
    if dtype == "int8":
        scales = jnp.where(amax > 0, amax / 127.0, 1.0)
        q = jnp.clip(jnp.round(w / scales[..., None, :]), -127, 127)
        return q.astype(jnp.int8), scales.astype(jnp.float32)
    if dtype == "fp8":
        f8 = fp8_dtype()
        if f8 is None:
            raise ValueError(
                "weight_dtype='fp8' but this jaxlib exposes no float8 "
                "dtype; use 'int8'")
        scales = jnp.where(amax > 0, amax / _FP8_E4M3_MAX, 1.0)
        return (w / scales[..., None, :]).astype(f8), \
            scales.astype(jnp.float32)
    raise ValueError("unknown weight dtype %r" % (dtype,))


def _attend_block(params, h, heads, seq_axis=None, vary_axes=None,
                  use_pallas=False):
    b, t, d = h.shape
    qkv = _rmsnorm(h) @ params["qkv"]
    q, k, v = (qkv[..., i * d:(i + 1) * d].reshape(b, t, heads,
                                                   d // heads)
               for i in range(3))
    if seq_axis is None:
        a = attention_reference(q, k, v, causal=True)
    else:
        # inside the full-mesh shard_map: t is this shard's chunk and
        # the K/V blocks ride the seq ring (flash recurrence);
        # use_pallas swaps in ring FLASH attention (per-hop Pallas
        # kernels, parallel/ring.py) when the chunk tiles
        local = _ring_attention_local
        if use_pallas:
            from ...parallel.ring import _ring_flash_local
            from ..flash_attention import flash_attention_supported
            if flash_attention_supported(t):
                local = _ring_flash_local
        a = local(
            q, k, v, axis_name=seq_axis, causal=True,
            scale=1.0 / math.sqrt(d // heads), vary_axes=vary_axes)
    return h + a.reshape(b, t, d) @ params["proj"]


def _block_sharded(params, h, *, heads, capacity, k, seq_axis=None,
                   vary_axes=None, use_pallas=False):
    """One transformer block INSIDE the full-mesh shard_map: expert
    leaves carry a leading local-expert dim (1), the MoE dispatch
    psums over the bound ``expert`` axis, and (when ``seq_axis`` is
    bound) attention rides the seq ring."""
    h = _attend_block(params, h, heads, seq_axis=seq_axis,
                      vary_axes=vary_axes, use_pallas=use_pallas)
    b, t, d = h.shape
    flat = _rmsnorm(h).reshape(b * t, d)
    moe = _moe_local({"w1": params["w1"], "w2": params["w2"]},
                     params["wr"], flat, expert_apply=_expert_ffn,
                     capacity=capacity, axis_name="expert", k=k)
    return h + moe.reshape(b, t, d)


def _block_oracle(params, h, *, heads, capacity, k, seq_shards=1):
    """Same block on one device: oracle MoE over the full [E,...]
    stack.  Attention is GLOBAL over T (ring attention equals full
    attention); the MoE queues replay per seq shard, matching the
    sharded path's per-chunk routing."""
    h = _attend_block(params, h, heads)
    b, t, d = h.shape
    normed = _rmsnorm(h)
    outs = []
    for c in range(seq_shards):
        chunk = normed[:, c * (t // seq_shards):
                       (c + 1) * (t // seq_shards)]
        flat = chunk.reshape(-1, d)
        moe = moe_reference(_expert_ffn,
                            {"w1": params["w1"], "w2": params["w2"]},
                            params["wr"], flat, capacity, k=k)
        outs.append(moe.reshape(b, t // seq_shards, d))
    return h + jnp.concatenate(outs, axis=1)


def flagship_apply(params, x, mesh, heads=2, microbatches=None,
                   capacity_factor=2.0, k=1, seq_axis=None,
                   use_pallas=False):
    """The pipelined sharded forward: x [B, T, D] with B over ``data``,
    blocks over ``pipe``, experts over ``expert`` — and T over
    ``seq_axis`` when given (ring attention inside each stage)."""
    from jax.sharding import PartitionSpec as P
    s = mesh.shape["pipe"]
    e = mesh.shape["expert"]
    # the pipeline shard takes p[0] of ITS slice and the MoE shard
    # routes to ITS local experts: stacked params larger than the mesh
    # axes would silently truncate to stage 0 / expert 0 (a 1-device
    # mesh once inflated a bench 4x this way) — fail loudly instead
    got_s = jax.tree_util.tree_leaves(params)[0].shape[0]
    got_e = params["w1"].shape[1]
    if got_s != s or got_e != e:
        raise ValueError(
            "flagship params are stacked for %d stages x %d experts "
            "but the mesh has pipe=%d x expert=%d — sizes must match "
            "(a mismatch would silently run a truncated model)"
            % (got_s, got_e, s, e))
    dp = mesh.shape.get("data", 1)
    sp = mesh.shape.get(seq_axis, 1) if seq_axis else 1
    m = microbatches if microbatches is not None else 2 * s
    b, t, d = x.shape
    tokens_per_mb = (b // dp // m) * (t // sp)
    capacity = moe_capacity(tokens_per_mb, e, capacity_factor, k)
    vary = tuple(a for a in ("data", seq_axis)
                 if a and a in mesh.shape) + ("pipe",)
    block = functools.partial(_block_sharded, heads=heads,
                              capacity=capacity, k=k,
                              seq_axis=seq_axis, vary_axes=vary,
                              use_pallas=use_pallas)
    specs = {"qkv": P("pipe"), "proj": P("pipe"), "wr": P("pipe"),
             "w1": P("pipe", "expert"), "w2": P("pipe", "expert")}
    x_spec = P("data", seq_axis) if seq_axis else P("data")
    fn = jax.shard_map(
        functools.partial(_gpipe_local, block_apply=block, n_stages=s,
                          microbatches=m, axis_name="pipe"),
        mesh=mesh,
        in_specs=({n: specs[n] for n in params}, x_spec),
        out_specs=x_spec)
    return fn(params, x)


def flagship_reference(params, x, heads=2, microbatches=None,
                       capacity_factor=2.0, k=1, data_shards=1,
                       pipe_stages=None, seq_shards=1):
    """Single-device oracle with the SAME capacity semantics: the
    sharded path routes each (data shard, microbatch, seq chunk)
    independently, so the oracle replays that slicing."""
    s = jax.tree_util.tree_leaves(params)[0].shape[0] \
        if pipe_stages is None else pipe_stages
    m = microbatches if microbatches is not None else 2 * s
    b, t, d = x.shape
    tokens_per_mb = (b // data_shards // m) * (t // seq_shards)
    e = params["wr"].shape[-1]
    capacity = moe_capacity(tokens_per_mb, e, capacity_factor, k)
    chunks = x.reshape(data_shards * m, b // data_shards // m, t, d)
    outs = []
    for chunk in chunks:
        h = chunk
        for i in range(s):
            params_i = jax.tree.map(lambda p: p[i], params)
            h = _block_oracle(params_i, h, heads=heads,
                              capacity=capacity, k=k,
                              seq_shards=seq_shards)
        outs.append(h)
    return jnp.concatenate(outs).reshape(b, t, d)


def demo_mesh():
    """The 8-device dp2 x pp2 x ep2 composition mesh (CPU-virtual in
    tests, a pod slice in production)."""
    return make_mesh({"data": 2, "pipe": 2, "expert": 2})


# -- causal decode over a paged KV cache --------------------------------------
#
# The serving-side face of the flagship model (ISSUE 6): a tied
# token embedding turns the [B, T, D] -> [B, T, D] trainer into a
# generate-style language model, and the per-layer K/V of every served
# sequence lives in the serving pool's fixed-size blocks
# (znicz.paged_attention) instead of a rectangular [B, T_max] cache.
# ``prefill`` runs the prompt through the dense causal forward ONCE
# while writing its K/V into the sequence's pool blocks;
# ``decode_step`` is the single-token iteration the token-level
# scheduler (serving/decode.py) compiles to ONE warm executable:
# [max_batch] token rows + the page-table operand, any mix of
# per-sequence lengths, zero steady-state recompiles.
#
# MoE routing at decode uses the oracle path with a no-drop capacity
# (every (token, choice) pair keeps a slot), so a token's output never
# depends on which other sequences share its batch row neighborhood —
# the row-isolation property the admit/retire tests assert.


def init_decode_params(stages, experts, d=16, heads=2, hidden=32,
                       vocab=64, seed=0):
    """:func:`init_params` plus a tied token embedding ``emb``
    [vocab, d] (logits = h @ emb.T)."""
    params = init_params(stages, experts, d=d, heads=heads,
                         hidden=hidden, seed=seed)
    rng = numpy.random.RandomState(seed + 1)
    params["emb"] = jnp.asarray(
        rng.standard_normal((vocab, d)) * 0.25, jnp.float32)
    return params


def _stacked(params):
    """The per-stage leaves (everything but the shared embedding).
    When the param tree carries quantized expert weights (``w1_q`` ...)
    those replace the f32 ``w1``/``w2`` leaves on every decode path."""
    names = ("qkv", "proj", "wr")
    if "w1_q" in params:
        names += ("w1_q", "w1_s", "w2_q", "w2_s")
    else:
        names += ("w1", "w2")
    return {n: params[n] for n in names}


def _moe_dense(p_i, h, k):
    """No-drop oracle MoE for ``h`` [N, d]: capacity covers every
    (token, choice) pair, so routing is per-token independent.
    Quantized expert leaves dispatch to the scaled-accumulate GEMM."""
    if "w1_q" in p_i:
        return moe_reference(
            _expert_ffn_quant,
            {n: p_i[n] for n in ("w1_q", "w1_s", "w2_q", "w2_s")},
            p_i["wr"], h, capacity=h.shape[0] * k, k=k)
    return moe_reference(_expert_ffn,
                         {"w1": p_i["w1"], "w2": p_i["w2"]},
                         p_i["wr"], h, capacity=h.shape[0] * k, k=k)


# -- quantized KV pools -------------------------------------------------------
#
# kv_dtype="int8" swaps each f32 pool array for {"q": int8 pool,
# "s": f32 per-block scales} and every pool write for a sequential
# quantized append: position off==0 resets the block's scale (so the
# bytes a block ends up with depend only on the tokens written into it,
# never on a previous tenant — the determinism prefix-chain dedupe
# relies on), later positions grow the scale monotonically and rescale
# the block's earlier rows when it grows.  With an unchanged scale the
# rescale is exact (round(q * 1) == q), so closed blocks are stable.


def _make_kv_pool(shape, kv_dtype):
    """One per-layer pool: f32 array, or {"q", "s"} leaves for int8
    (``s`` is the [num_blocks, heads] scale array the kernel
    prefetches)."""
    if kv_dtype == "int8":
        return {"q": jnp.zeros(shape, jnp.int8),
                "s": jnp.zeros((shape[0], shape[2]), jnp.float32)}
    return jnp.zeros(shape, jnp.float32)


def _kv_arrays(pool):
    """(data, scales-or-None) view of a pool of either dtype."""
    if isinstance(pool, dict):
        return pool["q"], pool["s"]
    return pool, None


def _append_kv(pool, blk, off, vals, kv_dtype):
    """Write ``vals`` at (blk, off).  f32: the exact ``.at[].set``
    the unquantized path always used.  int8: per-position sequential
    quantized append (see module note above); ``blk``/``off`` may be
    [N] or [B, S] (flattened row-major, so positions within a row stay
    in causal order)."""
    if kv_dtype != "int8":
        return pool.at[blk, off].set(vals)
    q, s = pool["q"], pool["s"]
    blk = blk.reshape(-1)
    off = off.reshape(-1)
    vals = vals.astype(jnp.float32).reshape((blk.shape[0],)
                                            + q.shape[2:])

    def body(t, carry):
        q, s = carry
        b, o, v = blk[t], off[t], vals[t]        # v: [H, hd]
        s_old = jnp.where(o == 0, 0.0, s[b])     # [H]
        s_new = jnp.maximum(s_old,
                            jnp.max(jnp.abs(v), axis=-1) / 127.0)
        s_safe = jnp.where(s_new > 0, s_new, 1.0)
        # ratio == 0 wipes a freshly opened block; ratio == 1 keeps
        # existing rows bit-exact when the scale did not grow
        ratio = jnp.where(s_old > 0, s_old / s_safe, 0.0)
        block = jnp.clip(jnp.round(q[b].astype(jnp.float32)
                                   * ratio[None, :, None]), -127, 127)
        row = jnp.clip(jnp.round(v / s_safe[:, None]), -127, 127)
        block = block.at[o].set(row).astype(jnp.int8)
        return q.at[b].set(block), s.at[b].set(s_new)

    q, s = jax.lax.fori_loop(0, int(blk.shape[0]), body, (q, s))
    return {"q": q, "s": s}


def _prefill_block(p_i, h, heads, k):
    """One dense causal block over the whole prompt; returns the block
    output and this layer's K/V ([T, H, hd]) for the cache."""
    b, t, d = h.shape
    qkv = _rmsnorm(h) @ p_i["qkv"]
    q, kk, vv = (qkv[..., i * d:(i + 1) * d].reshape(b, t, heads,
                                                     d // heads)
                 for i in range(3))
    a = attention_reference(q, kk, vv, causal=True)
    h = h + a.reshape(b, t, d) @ p_i["proj"]
    moe = _moe_dense(p_i, _rmsnorm(h).reshape(b * t, d), k)
    return h + moe.reshape(b, t, d), kk[0], vv[0]


def prefill(params, tokens, length, k_pools, v_pools, block_row, *,
            heads=2, block_size=8, k=1, kv_dtype="f32"):
    """Prompt pass: dense causal forward over ``tokens`` [T_bucket]
    (padded; ``length`` valid), writing each layer's K/V for positions
    < length into the pool blocks named by ``block_row`` [max_blocks].
    Returns (first generated token, k_pools, v_pools).  jit-able; one
    executable per T bucket."""
    t = int(tokens.shape[0])
    h = params["emb"][tokens][None]              # [1, T, d]
    stacked = _stacked(params)
    stages = stacked["qkv"].shape[0]
    pos = jnp.arange(t)
    valid = pos < length
    # invalid positions scatter into physical block 0 — the pool's
    # reserved trash block, never owned by a live sequence
    blk = jnp.where(valid, block_row[pos // block_size], 0)
    off = pos % block_size
    new_k, new_v = [], []
    for i in range(stages):
        p_i = jax.tree.map(lambda p: p[i], stacked)
        h, kk, vv = _prefill_block(p_i, h, heads, k)
        new_k.append(_append_kv(k_pools[i], blk, off, kk, kv_dtype))
        new_v.append(_append_kv(v_pools[i], blk, off, vv, kv_dtype))
    logits = h[0, length - 1] @ params["emb"].T
    token = jnp.argmax(logits).astype(jnp.int32)
    return token, tuple(new_k), tuple(new_v)


def prefill_chunk(params, tokens, start, length, k_pools, v_pools,
                  block_row, *, heads=2, block_size=8, k=1,
                  kv_dtype="f32"):
    """One fixed-size prefill chunk: positions ``start .. start+C-1``
    of a prompt whose earlier K/V — resident prefix blocks reused from
    the pool plus chunks already executed — are read back THROUGH the
    page-table row, not recomputed.  Per-layer: write this chunk's K/V
    into its pool slots, then ragged paged attention with per-query
    causal lengths (znicz.paged_attention.paged_prefill_attention).

    Static shapes: [C] tokens, scalar start/length — ONE executable
    covers every chunk of every prompt, which is what lets the
    scheduler interleave prefill chunks with decode steps instead of
    stalling the batch on a monolithic ladder call.  Returns (token,
    pools); the token is the first generated token and is only
    meaningful on the final chunk (``start + C >= length``).
    """
    from ..paged_attention import paged_prefill_attention
    c = int(tokens.shape[0])
    h = params["emb"][tokens][None]              # [1, C, d]
    stacked = _stacked(params)
    stages = stacked["qkv"].shape[0]
    d = h.shape[-1]
    hd = d // heads
    pos = start + jnp.arange(c)
    valid = pos < length
    # invalid positions scatter into the reserved trash block
    blk = jnp.where(valid, block_row[pos // block_size], 0)
    off = pos % block_size
    k_pools, v_pools = list(k_pools), list(v_pools)
    for i in range(stages):
        p_i = jax.tree.map(lambda p: p[i], stacked)
        qkv = _rmsnorm(h) @ p_i["qkv"]           # [1, C, 3d]
        q, kk, vv = (qkv[..., j * d:(j + 1) * d].reshape(1, c, heads,
                                                         hd)
                     for j in range(3))
        k_pools[i] = _append_kv(k_pools[i], blk, off, kk[0], kv_dtype)
        v_pools[i] = _append_kv(v_pools[i], blk, off, vv[0], kv_dtype)
        kd, ks = _kv_arrays(k_pools[i])
        vd, vs = _kv_arrays(v_pools[i])
        a = paged_prefill_attention(q[0], kd, vd,
                                    block_row, start, length,
                                    scale=1.0 / math.sqrt(hd),
                                    k_scales=ks, v_scales=vs)
        h = h + a.reshape(1, c, d) @ p_i["proj"]
        moe = _moe_dense(p_i, _rmsnorm(h).reshape(c, d), k)
        h = h + moe.reshape(1, c, d)
    last = jnp.clip(length - 1 - start, 0, c - 1)
    logits = h[0, last] @ params["emb"].T
    return (jnp.argmax(logits).astype(jnp.int32), tuple(k_pools),
            tuple(v_pools))


def _decode_block(p_i, h, k_pool_i, v_pool_i, page_table, lengths,
                  blk, off, heads, k, kv_dtype="f32"):
    """One single-token block: write this token's K/V into its pool
    slot, then ragged paged attention over the whole cached history
    (lengths + 1 includes the token just written)."""
    from ..paged_attention import paged_attention
    b, d = h.shape
    hd = d // heads
    qkv = _rmsnorm(h) @ p_i["qkv"]               # [B, 3d]
    q, kk, vv = (qkv[:, i * d:(i + 1) * d].reshape(b, heads, hd)
                 for i in range(3))
    k_pool_i = _append_kv(k_pool_i, blk, off, kk, kv_dtype)
    v_pool_i = _append_kv(v_pool_i, blk, off, vv, kv_dtype)
    kd, ks = _kv_arrays(k_pool_i)
    vd, vs = _kv_arrays(v_pool_i)
    a = paged_attention(q, kd, vd, page_table, lengths + 1,
                        scale=1.0 / math.sqrt(hd),
                        k_scales=ks, v_scales=vs)
    h = h + a.reshape(b, d) @ p_i["proj"]
    return h + _moe_dense(p_i, _rmsnorm(h), k), k_pool_i, v_pool_i


def decode_step(params, k_pools, v_pools, page_table, lengths, tokens,
                *, heads=2, block_size=8, k=1, kv_dtype="f32",
                with_logits=False):
    """One token for every row: embed ``tokens`` [B], write each row's
    K/V at position ``lengths[row]``, attend through the page table,
    return (next greedy tokens [B], k_pools, v_pools).

    Static shapes throughout — max-batch rows and the [B, max_blocks]
    page table — so the serving scheduler compiles this ONCE and runs
    arbitrary admit/retire mixes against the same executable.  Padding
    rows (lengths == 0 with an all-zero table row) write into the trash
    block and produce ignored tokens.
    """
    b = int(tokens.shape[0])
    h = params["emb"][tokens]                    # [B, d]
    stacked = _stacked(params)
    stages = stacked["qkv"].shape[0]
    rows = jnp.arange(b)
    blk = page_table[rows, lengths // block_size]
    off = lengths % block_size
    k_pools, v_pools = list(k_pools), list(v_pools)
    for i in range(stages):
        p_i = jax.tree.map(lambda p: p[i], stacked)
        h, k_pools[i], v_pools[i] = _decode_block(
            p_i, h, k_pools[i], v_pools[i], page_table, lengths, blk,
            off, heads, k, kv_dtype=kv_dtype)
    logits = h @ params["emb"].T                 # [B, V]
    out = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if with_logits:
        return out, tuple(k_pools), tuple(v_pools), logits
    return out, tuple(k_pools), tuple(v_pools)


def _verify_block(p_i, h, k_pool_i, v_pool_i, page_table, lengths,
                  blk, off, heads, k, kv_dtype="f32"):
    """One multi-token block of the speculative verify pass: write all
    S fed tokens' K/V into their pool slots, then ragged verify
    attention — per-position causal lengths keep query ``i`` blind to
    the drafts after it (znicz.paged_attention.paged_verify_attention).
    """
    from ..paged_attention import paged_verify_attention
    b, s, d = h.shape
    hd = d // heads
    qkv = _rmsnorm(h) @ p_i["qkv"]               # [B, S, 3d]
    q, kk, vv = (qkv[..., i * d:(i + 1) * d].reshape(b, s, heads, hd)
                 for i in range(3))
    k_pool_i = _append_kv(k_pool_i, blk, off, kk, kv_dtype)
    v_pool_i = _append_kv(v_pool_i, blk, off, vv, kv_dtype)
    kd, ks = _kv_arrays(k_pool_i)
    vd, vs = _kv_arrays(v_pool_i)
    a = paged_verify_attention(q, kd, vd, page_table,
                               lengths, scale=1.0 / math.sqrt(hd),
                               k_scales=ks, v_scales=vs)
    h = h + a.reshape(b, s, d) @ p_i["proj"]
    moe = _moe_dense(p_i, _rmsnorm(h).reshape(b * s, d), k)
    return h + moe.reshape(b, s, d), k_pool_i, v_pool_i


def verify_step(params, k_pools, v_pools, page_table, lengths, tokens,
                *, heads=2, block_size=8, k=1, kv_dtype="f32"):
    """Speculative verify: ``tokens`` [B, S] is each row's next input
    plus its S-1 draft tokens.  Every position is written at
    ``lengths[row] + i`` and attended with causal length
    ``lengths[row] + i + 1``, so ``out[:, i]`` is the target's greedy
    next token given the history plus fed tokens ``0 .. i`` — exactly
    the token plain decode would emit at that step when the drafts
    before it are all correct.  One executable per (B, S) — the ragged
    kernel absorbs any mix of per-row lengths.

    Writes past a row's page-table capacity scatter into the trash
    block (only ever possible for draft positions past the row's
    remaining token budget, whose outputs the scheduler discards).
    The MoE stays the no-drop oracle over the flattened [B*S] tokens,
    so rows remain isolated from each other AND positions from their
    own rejected tails.
    """
    b, s = int(tokens.shape[0]), int(tokens.shape[1])
    h = params["emb"][tokens]                    # [B, S, d]
    stacked = _stacked(params)
    stages = stacked["qkv"].shape[0]
    nb = page_table.shape[1]
    rows = jnp.arange(b)[:, None]
    pos = lengths[:, None] + jnp.arange(s)[None, :]
    blk = jnp.where(pos < nb * block_size,
                    page_table[rows, jnp.minimum(pos // block_size,
                                                 nb - 1)], 0)
    off = pos % block_size
    k_pools, v_pools = list(k_pools), list(v_pools)
    for i in range(stages):
        p_i = jax.tree.map(lambda p: p[i], stacked)
        h, k_pools[i], v_pools[i] = _verify_block(
            p_i, h, k_pools[i], v_pools[i], page_table, lengths, blk,
            off, heads, k, kv_dtype=kv_dtype)
    logits = h @ params["emb"].T                 # [B, S, V]
    return (jnp.argmax(logits, axis=-1).astype(jnp.int32),
            tuple(k_pools), tuple(v_pools))


def generate_reference(params, prompt, n_new, heads=2, k=1):
    """Cache-free greedy oracle: rerun the full dense causal forward
    over the whole history for every generated token.  O(T^2) per
    token — tests only."""
    tokens = [int(t) for t in prompt]
    stacked = _stacked(params)
    stages = stacked["qkv"].shape[0]
    out = []
    for _ in range(n_new):
        h = params["emb"][jnp.asarray(tokens, jnp.int32)][None]
        for i in range(stages):
            p_i = jax.tree.map(lambda p: p[i], stacked)
            h, _, _ = _prefill_block(p_i, h, heads, k)
        logits = h[0, -1] @ params["emb"].T
        nxt = int(jnp.argmax(logits))
        out.append(nxt)
        tokens.append(nxt)
    return out


class FlagshipDecodeModel:
    """The decode-serving adapter: flagship params + the jit-able
    prefill / decode-step closures the token-level scheduler
    (serving/decode.py) compiles.  ``kind = "decode"`` is what
    ModelRegistry.add dispatches on."""

    kind = "decode"
    #: KV-cache precisions this model's factories accept (the
    #: scheduler checks this before forwarding a non-default kv_dtype)
    kv_dtypes = ("f32", "int8")

    def __init__(self, params=None, *, stages=2, experts=2, d=16,
                 heads=2, hidden=32, vocab=64, k=1, seed=0,
                 kv_dtype="f32", weight_dtype="f32"):
        if params is None:
            params = init_decode_params(stages, experts, d=d,
                                        heads=heads, hidden=hidden,
                                        vocab=vocab, seed=seed)
        if kv_dtype not in self.kv_dtypes:
            raise ValueError("kv_dtype=%r not in %r"
                             % (kv_dtype, self.kv_dtypes))
        if weight_dtype != "f32":
            params = dict(params)
            for name in ("w1", "w2"):
                q, s = _quantize_weight_stack(params[name],
                                              weight_dtype)
                params[name + "_q"], params[name + "_s"] = q, s
        self.kv_dtype = kv_dtype
        self.weight_dtype = weight_dtype
        self.params = params
        self.heads = int(heads)
        self.k = int(k)
        self.layers = int(params["qkv"].shape[0])
        self.vocab = int(params["emb"].shape[0])
        self.d = int(params["emb"].shape[1])
        if self.d % self.heads:
            raise ValueError("d=%d not divisible by heads=%d"
                             % (self.d, self.heads))
        self.head_dim = self.d // self.heads
        self._draft_table = None

    def _kv(self, kv_dtype):
        return self.kv_dtype if kv_dtype is None else kv_dtype

    def make_pools(self, num_blocks, block_size, kv_dtype=None):
        """Fresh zeroed per-layer K and V pools
        ([num_blocks, block_size, H, hd] x layers); int8 pools are
        {"q", "s"} leaves per layer."""
        dt = self._kv(kv_dtype)
        shape = (int(num_blocks), int(block_size), self.heads,
                 self.head_dim)
        k_pools = tuple(_make_kv_pool(shape, dt)
                        for _ in range(self.layers))
        v_pools = tuple(_make_kv_pool(shape, dt)
                        for _ in range(self.layers))
        return k_pools, v_pools

    def prefill_fn(self, block_size, kv_dtype=None):
        """(tokens, length, k_pools, v_pools, block_row) ->
        (first token, pools) — close over the static geometry."""
        params, heads, k = self.params, self.heads, self.k
        dt = self._kv(kv_dtype)

        def fn(tokens, length, k_pools, v_pools, block_row):
            return prefill(params, tokens, length, k_pools, v_pools,
                           block_row, heads=heads,
                           block_size=block_size, k=k, kv_dtype=dt)
        return fn

    def prefill_chunk_fn(self, block_size, kv_dtype=None):
        """(tokens[C], start, length, k_pools, v_pools, block_row) ->
        (token, pools) — the one-executable chunked-prefill step."""
        params, heads, k = self.params, self.heads, self.k
        dt = self._kv(kv_dtype)

        def fn(tokens, start, length, k_pools, v_pools, block_row):
            return prefill_chunk(params, tokens, start, length,
                                 k_pools, v_pools, block_row,
                                 heads=heads, block_size=block_size,
                                 k=k, kv_dtype=dt)
        return fn

    def decode_fn(self, block_size, kv_dtype=None):
        """(k_pools, v_pools, page_table, lengths, tokens) ->
        (next tokens, pools)."""
        params, heads, k = self.params, self.heads, self.k
        dt = self._kv(kv_dtype)

        def fn(k_pools, v_pools, page_table, lengths, tokens):
            return decode_step(params, k_pools, v_pools, page_table,
                               lengths, tokens, heads=heads,
                               block_size=block_size, k=k, kv_dtype=dt)
        return fn

    def logits_fn(self, block_size, kv_dtype=None):
        """Like :meth:`decode_fn` but also returns the [B, V] logits —
        the probe/bench hook for measuring quantization error against
        the f32 oracle."""
        params, heads, k = self.params, self.heads, self.k
        dt = self._kv(kv_dtype)

        def fn(k_pools, v_pools, page_table, lengths, tokens):
            return decode_step(params, k_pools, v_pools, page_table,
                               lengths, tokens, heads=heads,
                               block_size=block_size, k=k, kv_dtype=dt,
                               with_logits=True)
        return fn

    def _unigram_table(self):
        """The drafter: a [vocab] next-token table distilled from the
        target by running it on every single-token prompt (a
        context-free student of the teacher — the cheapest drafter
        that still agrees with the target more often than chance).
        Computed once, host-side, on first use."""
        if self._draft_table is None:
            h = self.params["emb"][jnp.arange(self.vocab)][:, None]
            stacked = _stacked(self.params)
            for i in range(self.layers):
                p_i = jax.tree.map(lambda p: p[i], stacked)
                h, _, _ = _prefill_block(p_i, h, self.heads, self.k)
            logits = h[:, 0] @ self.params["emb"].T
            self._draft_table = jnp.argmax(
                logits, axis=-1).astype(jnp.int32)
        return self._draft_table

    def draft_fn(self, block_size, depth, kv_dtype=None):
        """(k_pools, v_pools, page_table, lengths, tokens[B]) ->
        draft tokens [B, depth].  Pure reads — drafting never writes
        the pools; acceptance is decided by the verify pass."""
        table = self._unigram_table()
        depth = int(depth)

        def fn(k_pools, v_pools, page_table, lengths, tokens):
            t = tokens
            outs = []
            for _ in range(depth):
                t = table[t]
                outs.append(t)
            return jnp.stack(outs, axis=1)
        return fn

    def verify_fn(self, block_size, depth, kv_dtype=None):
        """(k_pools, v_pools, page_table, lengths, tokens[B, depth+1])
        -> (out tokens [B, depth+1], pools) — the one-pass multi-token
        verify the scheduler compiles once per speculation depth."""
        params, heads, k = self.params, self.heads, self.k
        dt = self._kv(kv_dtype)

        def fn(k_pools, v_pools, page_table, lengths, tokens):
            return verify_step(params, k_pools, v_pools, page_table,
                               lengths, tokens, heads=heads,
                               block_size=block_size, k=k, kv_dtype=dt)
        return fn


def train_step(params, x, target, mesh, lr=0.05, **kwargs):
    """One fused SGD step of the full composition; jit-able."""
    def loss_fn(p):
        y = flagship_apply(p, x, mesh, **kwargs)
        return ((y - target) ** 2).mean()

    loss, grads = jax.value_and_grad(loss_fn)(params)
    return loss, jax.tree.map(lambda p, g: p - lr * g, params, grads)
