"""MnistSimple: fully-connected MNIST classifier.

Re-creation of the Znicz MnistSimple sample (absent submodule; topology and
its published baseline — 1.48 % validation error with a 100-tanh + 10-softmax
net — from /root/reference/docs/source/manualrst_veles_algorithms.rst:25-31).

Follows the reference's sample convention: the module exposes
``run(load, main)`` for the CLI (`python -m veles_tpu mnist.py config.py`)
plus a direct :func:`create_workflow` for programmatic use.
"""

import numpy

from ...config import root
from ...loader.fullbatch import FullBatchLoader
from ...loader.base import TEST, VALID, TRAIN
from ...datasets import load_digits_idx
from ..standard_workflow import StandardWorkflow

root.mnist.update({
    "loader": {"minibatch_size": 60, "normalization_type": "range_linear"},
    "layers": [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 100},
         "<-": {"learning_rate": 0.03, "weights_decay": 0.0,
                "gradient_moment": 0.9}},
        {"type": "softmax", "->": {"output_sample_shape": 10},
         "<-": {"learning_rate": 0.03, "weights_decay": 0.0,
                "gradient_moment": 0.9}},
    ],
    "decision": {"max_epochs": 25, "fail_iterations": 50},
})


class MnistLoader(FullBatchLoader):
    """MNIST-format digits: real IDX files when present, else the
    committed fixture archives, else the synthetic twin
    (``provenance`` records which; ``is_real`` means true MNIST)."""

    MAPPING = "mnist_loader"

    def __init__(self, workflow, **kwargs):
        self.n_train = kwargs.pop("n_train", None)
        self.n_valid = kwargs.pop("n_valid", None)
        self.use_fixture = kwargs.pop("use_fixture", True)
        super().__init__(workflow, **kwargs)

    def load_data(self):
        (ti, tl), (vi, vl), self.provenance = load_digits_idx(
            self.n_train, self.n_valid, fixture=self.use_fixture)
        self.is_real = self.provenance == "real"
        data = numpy.concatenate([vi, ti]).astype(numpy.float32)
        self.original_data.mem = data.reshape(len(data), -1)
        self.original_labels = list(numpy.concatenate([vl, tl]))
        self.class_lengths[TEST] = 0
        self.class_lengths[VALID] = len(vi)
        self.class_lengths[TRAIN] = len(ti)


def create_workflow(fused=True, **overrides):
    from . import build_standard
    return build_standard(root.mnist, "MnistSimple", MnistLoader, "softmax",
                          fused=fused, **overrides)

def run(load, main):
    """CLI convention (reference manualrst_veles_workflow_creation.rst:
    30-39): the framework calls run(load, main)."""
    load(create_workflow)
    main()
