"""Gradient units for pooling layers.

Re-creation of ``veles.znicz.gd_pooling`` (absent; SURVEY.md §2.9):
GDMaxPooling (route error to the argmax element), GDAvgPooling (spread
error uniformly), GDMaxAbsPooling.  All are parameterless; the error
routing is the vjp of the forward — XLA emits the select-and-scatter
kernel the reference hand-writes.
"""

from .nn_units import GenericVJPBackward


class GDPoolingBase(GenericVJPBackward):
    hide_from_registry = True


class GDMaxPooling(GDPoolingBase):
    MAPPING = "max_pooling"


class GDAvgPooling(GDPoolingBase):
    MAPPING = "avg_pooling"


class GDMaxAbsPooling(GDPoolingBase):
    MAPPING = "maxabs_pooling"


class GDStochasticPooling(GDPoolingBase):
    """Graph-mode backward through the SAME stochastic draw the forward
    made (regenerated from its recorded key); eval minibatches route
    through the expected-value forward."""

    MAPPING = "stochastic_pooling"

    def tpu_init(self):
        self._jitted_bwd_ = self.backward  # key varies per minibatch

    def backward(self, params, x, y, err_output, n_valid=None):
        import jax
        fwd = self.forward_unit
        key = fwd.last_key
        if key is None:
            fn = lambda xx: fwd.apply({}, xx)          # noqa: E731
        else:
            fn = lambda xx: fwd.apply_train({}, xx, key)  # noqa: E731
        _, pullback = jax.vjp(fn, x)
        (err_in,) = pullback(err_output)
        return err_in, {}

    def backward_numpy(self, params, x, y, err_output, n_valid=None):
        import numpy
        err_in, grads = self.backward(params, x, y, err_output, n_valid)
        return numpy.asarray(err_in), grads


class GDStochasticAbsPooling(GDStochasticPooling):
    MAPPING = "stochastic_abs_pooling"
