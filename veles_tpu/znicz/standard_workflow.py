"""StandardWorkflow: config-driven NN training topology builder.

Re-creation of ``veles.znicz.standard_workflow.StandardWorkflow`` (absent;
documented at /root/reference/docs/source/
manualrst_veles_workflow_creation.rst:101-146): builds
repeater → loader → forwards[] → evaluator → decision → gds[] (reverse) →
loop from a ``layers`` config list, each entry
``{"type": <MAPPING>, "->": {forward kwargs}, "<-": {gd kwargs}}`` (flat
kwargs are accepted too and routed by prefix knowledge).

Two execution modes:

- **fused** (default on a real device): forwards trace into ONE jitted,
  donated train-step (:class:`FusedTrainStep`); the graph carries only the
  host-side control units (loader → fused → decision).  This is the
  TPU-idiomatic hot loop (SURVEY.md §7).
- **graph**: the classic per-unit chain with explicit GD units — the
  parity/debug path, and the shape the reference actually executes.

Both modes share the same forward units, weights, and decision logic, so a
workflow can be built fused for speed and inspected per-unit.
"""

from ..plumbing import Repeater
from ..registry import UnitRegistry
from ..workflow import Workflow
from .nn_units import ForwardBase, GradientDescentBase
from .all2all import All2AllSoftmax
from .evaluator import EvaluatorSoftmax, EvaluatorMSE
from .decision import DecisionGD, DecisionMSE
from .fused import FusedTrainStep


def parse_mcdnnic_topology(topology, parameters=None):
    """MCDNN string notation → a ``layers`` config list.

    The reference accepted topologies "like in the AlexNet paper"
    (manualrst_veles_workflow_creation.rst:41-47, used by the Lines
    sample): dash-separated tokens, e.g. ``12x256x256-32C5-MP2-64C5-
    MP2-1024N-10N``:

    - ``CxHxW`` (first token, optional) — the input spec, informational;
    - ``<n>C<k>`` — conv, n kernels of k x k (strict-ReLU);
    - ``MP<k>`` / ``AP<k>`` — max/avg pooling k x k, stride k;
    - ``<n>N`` — fully-connected with n neurons; tanh for hidden
      layers, softmax for the final one.

    ``parameters`` ({"->": {...}, "<-": {...}} or flat) seeds every
    generated layer's config (the reference's ``mcdnnic_parameters``)."""
    import re
    params = dict(parameters or {})
    fwd_base = dict(params.get("->", {}))
    gd_base = dict(params.get("<-", {}))
    flat = {k: v for k, v in params.items() if k not in ("->", "<-")}
    tokens = [t for t in str(topology).split("-") if t]
    if tokens and re.fullmatch(r"\d+(x\d+)+", tokens[0]):
        tokens = tokens[1:]  # input spec: shapes come from the loader
    layers = []
    for i, tok in enumerate(tokens):
        last = i == len(tokens) - 1
        m = re.fullmatch(r"(\d+)C(\d+)", tok)
        if m:
            n, k = int(m.group(1)), int(m.group(2))
            layers.append({"type": "conv_str",
                           "->": {"n_kernels": n, "kx": k, "ky": k,
                                  **fwd_base},
                           "<-": dict(gd_base), **flat})
            continue
        m = re.fullmatch(r"(M|A)P(\d+)", tok)
        if m:
            k = int(m.group(2))
            layers.append({"type": ("max_pooling" if m.group(1) == "M"
                                    else "avg_pooling"),
                           "->": {"kx": k, "ky": k, "sliding": (k, k)}})
            continue
        m = re.fullmatch(r"(\d+)N", tok)
        if m:
            n = int(m.group(1))
            layers.append({"type": "softmax" if last else "all2all_tanh",
                           "->": {"output_sample_shape": n, **fwd_base},
                           "<-": dict(gd_base), **flat})
            continue
        raise ValueError(
            "unrecognized mcdnnic token %r in %r (expected <n>C<k>, "
            "MP<k>/AP<k>, <n>N or an CxHxW input spec)"
            % (tok, topology))
    if not layers:
        raise ValueError("mcdnnic_topology %r has no layers" % topology)
    return layers


def _find_pair(type_name):
    """Resolve a layer-type MAPPING to its (forward, gd) classes via the
    unit registry (the reference resolves through its own MAPPING registry,
    manualrst_veles_workflow_parameters.rst:469)."""
    fwd = gd = None
    for cls in UnitRegistry.units.values():
        if getattr(cls, "MAPPING", None) != type_name:
            continue
        if issubclass(cls, ForwardBase):
            fwd = cls
        elif issubclass(cls, GradientDescentBase):
            gd = cls
    if fwd is None:
        raise ValueError("unknown layer type %r" % type_name)
    return fwd, gd


class StandardWorkflow(Workflow):
    """repeater → loader → forwards → evaluator → decision → gds → loop."""

    hide_from_registry = True

    def __init__(self, workflow=None, **kwargs):
        super().__init__(workflow, **kwargs)
        if kwargs.get("mcdnnic_topology"):
            if kwargs.get("layers"):
                raise ValueError(
                    "pass layers= OR mcdnnic_topology=, not both")
            self.layers_config = parse_mcdnnic_topology(
                kwargs["mcdnnic_topology"],
                kwargs.get("mcdnnic_parameters"))
        else:
            self.layers_config = list(kwargs.get("layers", ()))
        self.loss_function = kwargs.get("loss_function", "softmax")
        self.fused = kwargs.get("fused", True)
        # whole-workflow compilation (veles_tpu.graphcomp): None =
        # follow root.common.engine.graph_compile (default off)
        self.graph_compile = kwargs.get("graph_compile", None)
        self.mesh = kwargs.get("mesh")           # jax.sharding.Mesh → SPMD
        self.model_axis = kwargs.get("model_axis")
        self.tp_mode = kwargs.get("tp_mode", "column")
        # epoch_scan: one lax.scan dispatch per class instead of one
        # dispatch per minibatch (FullBatch loaders only)
        self.epoch_scan = kwargs.get("epoch_scan", False)
        self.decision_config = dict(kwargs.get("decision", {}))
        self.loader_config = dict(kwargs.get("loader", {}))
        # async input pipeline lookahead for the per-step path; None =
        # follow root.common.loader.prefetch_depth (default 2, 0 = sync)
        self.prefetch_depth = self.loader_config.pop("prefetch_depth",
                                                     None)
        self.trainer_config = dict(kwargs.get("trainer", {}))
        self.snapshotter_config = kwargs.get("snapshotter")  # dict|None
        self.snapshotter = None
        self.web_status = kwargs.get("web_status", False)
        self.status_reporter = None
        loader_factory = kwargs.get("loader_factory")
        if loader_factory is None:
            raise ValueError("StandardWorkflow requires loader_factory")
        self.repeater = Repeater(self)
        self.loader = loader_factory(self, **self.loader_config)
        self.forwards = []
        self.gds = []
        self.fused_step = None
        self.evaluator = None
        self.decision = None
        self._build()

    # -- construction --------------------------------------------------------
    def _split_layer_config(self, cfg):
        cfg = dict(cfg)
        type_name = cfg.pop("type")
        fwd_kwargs = dict(cfg.pop("->", {}))
        gd_kwargs = dict(cfg.pop("<-", {}))
        # flat keys: route the known GD hyperparameters, rest to forward
        gd_keys = {"learning_rate", "learning_rate_bias", "weights_decay",
                   "weights_decay_bias", "l1_vs_l2", "l1_vs_l2_bias",
                   "gradient_moment", "solver", "solver_parameters",
                   "factor_ortho"}
        for k, v in cfg.items():
            (gd_kwargs if k in gd_keys else fwd_kwargs).setdefault(k, v)
        return type_name, fwd_kwargs, gd_kwargs

    def _build(self):
        self.repeater.link_from(self.start_point)
        self.loader.link_from(self.repeater)

        prev = self.loader
        gd_pairs = []
        for cfg in self.layers_config:
            type_name, fwd_kwargs, gd_kwargs = self._split_layer_config(cfg)
            fwd_cls, gd_cls = _find_pair(type_name)
            fwd = fwd_cls(self, **fwd_kwargs)
            fwd.link_from(prev)
            if prev is self.loader:
                fwd.link_attrs(self.loader, ("input", "minibatch_data"))
            else:
                fwd.link_attrs(prev, ("input", "output"))
            if fwd.stochastic:
                # stochastic units draw per-train-minibatch keys in graph
                # mode; they watch the loader's class to know when
                fwd.link_attrs(self.loader, "minibatch_class")
            self.forwards.append(fwd)
            gd_pairs.append((gd_cls, gd_kwargs))
            prev = fwd

        # evaluator (graph mode only — fused mode computes the loss and
        # metrics inside the step) + decision
        if self.loss_function == "softmax":
            if not self.fused:
                self.evaluator = EvaluatorSoftmax(self)
            self.decision = DecisionGD(self, **self.decision_config)
        else:
            if not self.fused:
                self.evaluator = EvaluatorMSE(self)
            self.decision = DecisionMSE(self, **self.decision_config)

        # instantiate GD units (shared by both modes: they own solver state
        # and hyperparameters; fused mode reads them, graph mode runs them)
        from .nn_units import GenericVJPBackward, ParamlessForward
        for (gd_cls, gd_kwargs), fwd in zip(gd_pairs, self.forwards):
            if gd_cls is None:
                if not isinstance(fwd, ParamlessForward):
                    raise ValueError(
                        "no GD unit registered for parameterized layer %r"
                        % type(fwd).MAPPING)
                gd_cls = GenericVJPBackward  # paramless structural layer
            gd = gd_cls(self, **gd_kwargs)
            gd.link_forward(fwd)
            self.gds.append(gd)

        if self.snapshotter_config is not None:
            cfg = dict(self.snapshotter_config)
            fmt = cfg.pop("format", None)
            if fmt is None:
                from ..config import root
                fmt = root.common.snapshot.get("format", "pickle")
            if fmt in ("shards", "sharded"):
                from ..checkpoint import SnapshotterToShards as snap_cls
            elif fmt in ("pickle", "file", None):
                from ..snapshotter import SnapshotterToFile as snap_cls
            else:
                from ..registry import MappedObjectsRegistry
                snap_cls = MappedObjectsRegistry.get("snapshotter", fmt)
            self.snapshotter = snap_cls(self, **cfg)
            self.snapshotter.link_decision(self.decision)
            # snapshot the moment validation improves — BEFORE the next
            # train pass mutates the weights — so a restored
            # ``validation_X`` snapshot really is the model that scored X;
            # without the valid_ended conjunct every train-minibatch pass
            # after an improvement would snapshot again
            self.snapshotter.skip = ~(self.decision.improved &
                                      self.loader.valid_ended)

        if self.web_status:
            # heartbeat side-branch: fires off the decision each epoch,
            # does not gate the training loop
            from ..web_status import StatusReporter
            cfg = self.web_status if isinstance(self.web_status, dict) \
                else {}
            self.status_reporter = StatusReporter(self, **cfg)
            self.status_reporter.link_from(self.decision)
            self.status_reporter.link_loader(self.loader)

        if self.fused:
            self._build_fused()
        else:
            self._build_graph()
        self.repeater.gate_block = self.decision.complete
        self.end_point.gate_block = ~self.decision.complete

    def _build_fused(self):
        # forwards/gds stay OUT of the control graph: FusedTrainStep traces
        # through them
        for fwd in self.forwards:
            fwd.unlink_all()
        from .misc_units import ZeroFiller
        for fwd in self.forwards:
            if isinstance(fwd, ZeroFiller):
                raise ValueError(
                    "zero_filler is graph-mode only; use Conv(grouping=N) "
                    "in fused workflows (see ZeroFiller docstring)")
        if self.epoch_scan:
            from ..mutable import Bool
            if self.mesh is not None:
                # the two big levers composed: one scan dispatch per
                # class AND dp/tp shardings over the mesh
                from ..parallel.scan import DistributedScanStep
                self.fused_step = DistributedScanStep(
                    self, self.forwards, self.gds, mesh=self.mesh,
                    loss=self.loss_function, model_axis=self.model_axis,
                    tp_mode=self.tp_mode, **self.trainer_config)
            else:
                from .scan_step import ScanEpochStep
                self.fused_step = ScanEpochStep(
                    self, self.forwards, self.gds,
                    loss=self.loss_function, **self.trainer_config)
            # the scan step drives the loader itself; the loader stays
            # linked (so it initializes before the scan step in dependency
            # order) but permanently blocked from running
            self.loader.gate_block = Bool(True)
            self.fused_step.link_from(self.repeater)
            self.fused_step.link_scan_loader(self.loader)
        elif self.mesh is not None:
            from ..parallel.dp import DistributedTrainStep
            self.fused_step = DistributedTrainStep(
                self, self.forwards, self.gds, mesh=self.mesh,
                loss=self.loss_function, model_axis=self.model_axis,
                tp_mode=self.tp_mode, **self.trainer_config)
            self.fused_step.link_from(self.loader)
            self.fused_step.link_loader(self.loader)
        else:
            self.fused_step = FusedTrainStep(
                self, self.forwards, self.gds, loss=self.loss_function,
                **self.trainer_config)
            self.fused_step.link_from(self.loader)
            self.fused_step.link_loader(self.loader)
            from ..loader.fullbatch import FullBatchLoader
            if isinstance(self.loader, FullBatchLoader):
                # HBM-resident dataset: gather rides inside the jitted
                # step — one executable launch per minibatch
                self.fused_step.link_fused_gather(self.loader)
        self.decision.link_from(self.fused_step)
        self.decision.link_loader(self.loader)
        self.decision.link_evaluator(self.fused_step)
        tail = self._link_snapshotter(self.decision)
        self.repeater.link_from(tail)
        self.end_point.link_from(tail)

    def _build_graph(self):
        last_fwd = self.forwards[-1]
        self.evaluator.link_from(last_fwd)
        self.evaluator.link_attrs(last_fwd, "output")
        if isinstance(last_fwd, All2AllSoftmax):
            self.evaluator.link_attrs(last_fwd, "max_idx")
        if self.loss_function == "softmax":
            self.evaluator.link_attrs(
                self.loader, ("labels", "minibatch_labels"),
                ("batch_size", "minibatch_size"))
        else:
            self.evaluator.link_attrs(
                self.loader, ("target", "minibatch_targets"),
                ("batch_size", "minibatch_size"))
        self.decision.link_from(self.evaluator)
        self.decision.link_loader(self.loader)
        self.decision.link_evaluator(self.evaluator)

        prev = self._link_snapshotter(self.decision)
        train_gate = self.make_train_gate(self.loader)
        for i in reversed(range(len(self.forwards))):
            gd = self.gds[i]
            gd.link_from(prev)
            gd.link_attrs(self.loader, ("batch_size", "minibatch_size"))
            if i == len(self.forwards) - 1:
                gd.link_attrs(self.evaluator, "err_output")
            else:
                gd.link_attrs(self.gds[i + 1], ("err_output", "err_input"))
            if i == 0:
                gd.need_err_input = False  # nothing below to backprop into
            gd.gate_skip = train_gate
            prev = gd
        self.repeater.link_from(prev)
        self.end_point.link_from(prev)

    def _link_snapshotter(self, tail):
        if self.snapshotter is None:
            return tail
        self.snapshotter.link_from(tail)
        return self.snapshotter

    def __getstate__(self):
        state = super().__getstate__()
        mesh = state.get("mesh")
        if mesh is not None and not isinstance(mesh, dict):
            # jax Device handles are process-local; snapshot the axis
            # geometry instead (the sharded steps do the same) and
            # rebuild over the restoring process's devices
            from ..parallel import mesh as mesh_mod
            state["mesh"] = mesh_mod.mesh_spec(mesh)
        return state

    def initialize(self, device=None, **kwargs):
        if isinstance(self.mesh, dict):   # restored from a snapshot
            from ..parallel import mesh as mesh_mod
            self.mesh = mesh_mod.mesh_for_spec(self.mesh)
        # cross-mesh restore: the workflow's mesh (spec-rebuilt above,
        # or a Mesh the caller assigned before initialize) overrides the
        # geometry the sharded step snapshotted for itself
        step = getattr(self, "fused_step", None)
        if self.mesh is not None and getattr(step, "mesh", None) is not None:
            step.mesh = self.mesh
        if self.restored_from_snapshot:
            self._relink_gates()
        result = super().initialize(device=device, **kwargs)
        self._maybe_attach_prefetcher(device)
        self._maybe_attach_graph_compiler()
        return result

    def _maybe_attach_graph_compiler(self):
        """Adopt whole-workflow compilation behind the
        ``root.common.engine.graph_compile`` knob (or the per-workflow
        ``graph_compile=`` ctor override).  In graph mode the per-unit
        chain traces into one compiled program per minibatch; in fused/
        scan/mesh modes the pre-fused step passes through as its own
        region, so flipping the knob never regresses the blessed path.
        getattr: snapshots written before the knob existed restore."""
        from ..config import root
        enabled = getattr(self, "graph_compile", None)
        if enabled is None:
            enabled = root.common.engine.get("graph_compile", False)
        if enabled:
            self.attach_graph_compiler()

    def _maybe_attach_prefetcher(self, device):
        """Overlap host minibatch prep with device compute on the
        per-step fused path (loader/prefetch.py).  The epoch-scan path
        already amortizes the whole class into one dispatch, and the
        multi-host distributed step re-places host batches itself, so
        both skip."""
        if not self.fused or self.epoch_scan or self.fused_step is None:
            return
        if getattr(self.fused_step, "_prefetch_unsupported_", False):
            return
        stage = bool(device is not None and
                     getattr(device, "exists", False))
        # getattr: snapshots written before the knob existed must still
        # restore (None = follow the global config default)
        self.attach_prefetcher(loader=self.loader,
                               depth=getattr(self, "prefetch_depth",
                                             None),
                               stage_to_device=stage)

    def _relink_gates(self):
        """Derived Bool expressions flatten to constants on pickle; rebuild
        them from the live Decision/loader after a restore."""
        from ..mutable import Bool
        self.repeater.gate_block = self.decision.complete
        self.end_point.gate_block = ~self.decision.complete
        self.decision.complete <<= False
        if self.snapshotter is not None:
            self.snapshotter.skip = ~(self.decision.improved &
                                      self.loader.valid_ended)
        if self.epoch_scan:
            self.loader.gate_block = Bool(True)
        if not self.fused:
            train_gate = self.make_train_gate(self.loader)
            for gd in self.gds:
                gd.gate_skip = train_gate

    def run(self):
        result = super().run()
        if self.fused_step is not None:
            self.fused_step.sync_weights()
        return result
