"""Multi-head attention forward/backward units.

Beyond the reference's 2015-era layer inventory (SURVEY.md §2.9 lists
none — the platform predates transformers), but squarely inside its
capability contract: "any topology the unit library can express, scaled
past one device".  On TPU that means attention must exist as a
first-class unit whose sequence dimension can shard over the mesh — the
long-context path (parallel/ring.py ring attention) is wired in here,
not bolted on.

Layout: input [B, T, D]; packed QKV projection ``weights`` (D, 3D),
output projection ``proj`` (D, D) + optional ``bias`` (D,).  The unit
follows every ForwardBase contract (pure ``apply``, params pytree,
export_params for the package archive), so it composes with
StandardWorkflow, the fused/epoch-scan trainers, snapshots, and the
mesh-sharded distributed step like any other layer; the backward is the
generic VJP pair (graph mode and fused mode agree by construction).
"""

import numpy

from ..memory import Array
from .nn_units import ForwardBase, GradientDescentBase


class MultiHeadAttention(ForwardBase):
    """Self-attention over [B, T, D] sequences.

    kwargs:
      heads: number of attention heads (must divide D);
      causal: autoregressive masking;
      window: sliding-window (Mistral-style) attention — position i
        sees keys in (i - window, i]; requires ``causal``; on the
        flash path, off-band blocks skip their MXU work;
      mesh/seq_axis/data_axis: when a ``jax.sharding.Mesh`` with a seq
        axis is given, attention runs as RING attention over it
        (sequence parallelism; parallel/ring.py) — the single-device
        math is identical;
      use_pallas: tri-state.  True/False force; unset (None) = AUTO:
        flash kernels whenever running on TPU (measured >= parity fwd
        and ahead on train steps, docs/PERF.md), the jnp oracle on
        CPU (interpret-mode kernels are orders slower).  Route
        attention through the Pallas flash kernels
        (znicz/flash_attention.py — O(block) VMEM, no materialized
        [T, T]; defaults to ``root.common.engine.use_pallas``).
        Applies on BOTH paths: single-device flash attention, and ring
        FLASH attention over the mesh (each hop's block math runs the
        flash kernels, parallel/ring.py ring-flash custom VJP).
    """

    MAPPING = "multihead_attention"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.heads = int(kwargs.get("heads", 1))
        self.causal = bool(kwargs.get("causal", False))
        self.window = kwargs.get("window")
        if self.window is not None:
            self.window = int(self.window)
            if not self.causal:
                raise ValueError("window requires causal=True")
            if self.window < 1:
                raise ValueError("window must be >= 1, got %d"
                                 % self.window)
        self.mesh = kwargs.get("mesh")
        self.seq_axis = kwargs.get("seq_axis", "seq")
        self.data_axis = kwargs.get("data_axis")
        from ..config import root
        # tri-state: True / False force; None (the default) = AUTO —
        # flash kernels on TPU where they measure >= parity (fwd) and
        # ahead (train), the jnp oracle elsewhere (CPU interpret mode
        # of the kernel is orders slower); docs/PERF.md round-5 A/Bs
        up = kwargs.get("use_pallas",
                        root.common.engine.get("use_pallas", None))
        self.use_pallas = up if up is None else bool(up)
        self.proj = Array()
        self.exports = ["weights", "proj", "bias"]

    def init_params(self):
        b, t, d = self.input_shape
        if d % self.heads:
            raise ValueError("heads=%d must divide model dim %d"
                             % (self.heads, d))
        stddev = self.weights_stddev or 1.0 / numpy.sqrt(d)
        self.fill_array(self.weights, (d, 3 * d), stddev,
                        self.weights_filling)
        self.fill_array(self.proj, (d, d), stddev, self.weights_filling)
        if self.include_bias:
            self.fill_array(self.bias, (d,), self.bias_stddev or stddev,
                            self.bias_filling)

    @property
    def params(self):
        p = {"weights": self.weights.devmem, "proj": self.proj.devmem}
        if self.include_bias and self.bias:
            p["bias"] = self.bias.devmem
        return p

    def set_params(self, params):
        if "weights" in params:
            self.weights.devmem = params["weights"]
        if "proj" in params:
            self.proj.devmem = params["proj"]
        if "bias" in params:
            self.bias.devmem = params["bias"]

    @property
    def host_params(self):
        p = super().host_params
        p["proj"] = self.proj.map_read()
        return p

    def set_host_params(self, params):
        super().set_host_params(params)
        if "proj" in params:
            self.proj.mem = numpy.asarray(params["proj"], numpy.float32)

    def output_shape_for(self, input_shape):
        return tuple(input_shape)

    def _resolved_use_pallas(self):
        from .nn_units import resolve_use_pallas
        return resolve_use_pallas(self.use_pallas, self.device,
                                  tpu_auto=True)

    def _attend(self, q, k, v):
        from ..parallel.ring import attention_reference, ring_attention
        use_pallas = self._resolved_use_pallas()
        if self.mesh is not None and self.seq_axis in self.mesh.shape:
            if self.window is not None:
                raise NotImplementedError(
                    "sliding-window attention over a seq mesh axis is "
                    "not implemented (a window <= T_local would never "
                    "need the ring anyway — shard other axes instead)")
            return ring_attention(q, k, v, self.mesh,
                                  seq_axis=self.seq_axis,
                                  data_axis=self.data_axis,
                                  causal=self.causal,
                                  use_pallas=use_pallas)
        if use_pallas:
            # the flash kernel pair: O(T*D) HBM traffic instead of the
            # oracle's materialized [T, T] scores (falls back to the
            # oracle internally when T can't be tiled)
            from .flash_attention import flash_attention
            return flash_attention(q, k, v, self.causal,
                                   window=self.window)
        return attention_reference(q, k, v, causal=self.causal,
                                   window=self.window)

    def apply(self, params, x):
        b, t, d = x.shape
        h = self.heads
        qkv = x @ params["weights"]                     # [B, T, 3D]
        q, k, v = (qkv[..., i * d:(i + 1) * d].reshape(b, t, h, d // h)
                   for i in range(3))
        out = self._attend(q, k, v).reshape(b, t, d)
        y = out @ params["proj"]
        if "bias" in params:
            y = y + params["bias"]
        return y

    def export_params(self):
        out = {"heads": int(self.heads), "causal": bool(self.causal),
               "include_bias": bool(self.include_bias)}
        if self.window is not None:
            out["window"] = int(self.window)
        return out


class GDMultiHeadAttention(GradientDescentBase):
    """Backward via the generic VJP of the forward's pure apply (the
    same chain rule the fused trainer differentiates)."""

    MAPPING = "multihead_attention"

    def backward(self, params, x, y, err_output, n_valid=None):
        if n_valid is None:
            n_valid = x.shape[0]
        return self.backward_via_vjp(params, x, err_output, n_valid)

    def backward_numpy(self, params, x, y, err_output, n_valid=None):
        err_in, grads = self.backward(params, x, y, err_output, n_valid)
        return (numpy.asarray(err_in) if err_in is not None else None,
                {k: numpy.asarray(v) for k, v in grads.items()})
