"""Dropout forward/backward units.

Re-creation of ``veles.znicz.dropout`` (absent; SURVEY.md §2.9).  Inverted
dropout: train-time ``x * bernoulli(1-p) / (1-p)``, eval-time identity.

Keys arrive as arguments (jit-safe, reproducible).  In graph mode the
forward records the key it drew for the minibatch and the backward
*regenerates* the same Bernoulli mask from it — exact, with no mask buffer
(the reference stores a mask array; regenerating from the counter-derived
key is free on TPU and keeps the unit stateless).
"""

from ..prng.random_generator import KeyTree
from .nn_units import ParamlessForward, GradientDescentBase


class DropoutForward(ParamlessForward):
    MAPPING = "dropout"
    stochastic = True

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.dropout_ratio = float(kwargs.get("dropout_ratio", 0.5))
        self.include_bias = False
        self.key_tree = kwargs.get("key_tree") or KeyTree(
            kwargs.get("seed", 42))

    def apply(self, params, x):
        return x

    def apply_train(self, params, x, key):
        import jax
        keep = 1.0 - self.dropout_ratio
        mask = jax.random.bernoulli(key, keep, x.shape)
        return x * mask / keep

    def apply_numpy(self, params, x):
        return x


    def export_params(self):
        return {"dropout_ratio": self.dropout_ratio}


class DropoutBackward(GradientDescentBase):
    """Regenerates the forward's mask from its recorded key and routes the
    error through it.  Not jitted: the key changes every minibatch, so the
    two elementwise ops run eagerly (XLA fuses them anyway)."""

    MAPPING = "dropout"

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("learning_rate", 0.0)
        super().__init__(workflow, **kwargs)

    def tpu_init(self):
        self._jitted_bwd_ = self._bwd_eager

    def _bwd_eager(self, params, x, y, err_output, n_valid=None):
        return self.backward(params, x, y, err_output, n_valid)

    def backward(self, params, x, y, err_output, n_valid=None):
        fwd = self.forward_unit
        key = fwd.last_key
        if key is None:
            return err_output, {}
        import jax
        keep = 1.0 - fwd.dropout_ratio
        mask = jax.random.bernoulli(key, keep, err_output.shape)
        return err_output * mask / keep, {}

    def backward_numpy(self, params, x, y, err_output, n_valid=None):
        import numpy
        err_in, grads = self.backward(params, x, y, err_output, n_valid)
        return numpy.asarray(err_in), grads