"""Standalone activation units.

Re-creation of ``veles.znicz.activation`` (absent; SURVEY.md §2.9):
Forward{Tanh,Sigmoid,RELU,StrictRELU,Log,TanhLog,SinCos,Mul} with matching
Backward units.  These exist for graphs that interleave activations between
non-activation layers (e.g. conv → norm → activation).
"""

import numpy

from .nn_units import (ForwardBase, ParamlessForward,  # noqa: F401
                       GradientDescentBase)
from . import activations


class ActivationForward(ParamlessForward):
    hide_from_registry = True
    ACTIVATION = None

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.activation = activations.get(self.ACTIVATION)
        self.include_bias = False

    def apply(self, params, x):
        return self.activation.fwd_jnp(x)

    def apply_numpy(self, params, x):
        return self.activation.fwd_np(x)


class ActivationBackward(GradientDescentBase):
    hide_from_registry = True
    ACTIVATION = None

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("learning_rate", 0.0)
        super().__init__(workflow, **kwargs)
        self.activation = activations.get(self.ACTIVATION)

    def backward(self, params, x, y, err_output, n_valid=None):
        return err_output * self.activation.deriv_jnp(y, x), {}

    def backward_numpy(self, params, x, y, err_output, n_valid=None):
        return err_output * self.activation.deriv_np(y, x), {}


class ForwardTanh(ActivationForward):
    MAPPING = "activation_tanh"
    ACTIVATION = "tanh"


class BackwardTanh(ActivationBackward):
    MAPPING = "activation_tanh"
    ACTIVATION = "tanh"


class ForwardSigmoid(ActivationForward):
    MAPPING = "activation_sigmoid"
    ACTIVATION = "sigmoid"


class BackwardSigmoid(ActivationBackward):
    MAPPING = "activation_sigmoid"
    ACTIVATION = "sigmoid"


class ForwardRELU(ActivationForward):
    MAPPING = "activation_relu"
    ACTIVATION = "relu"


class BackwardRELU(ActivationBackward):
    MAPPING = "activation_relu"
    ACTIVATION = "relu"


class ForwardStrictRELU(ActivationForward):
    MAPPING = "activation_str"
    ACTIVATION = "strict_relu"


class BackwardStrictRELU(ActivationBackward):
    MAPPING = "activation_str"
    ACTIVATION = "strict_relu"


class ForwardLog(ActivationForward):
    MAPPING = "activation_log"
    ACTIVATION = "log"


class BackwardLog(ActivationBackward):
    MAPPING = "activation_log"
    ACTIVATION = "log"


class ForwardTanhLog(ActivationForward):
    MAPPING = "activation_tanhlog"
    ACTIVATION = "tanhlog"


class BackwardTanhLog(ActivationBackward):
    MAPPING = "activation_tanhlog"
    ACTIVATION = "tanhlog"


class ForwardSinCos(ActivationForward):
    MAPPING = "activation_sincos"
    ACTIVATION = "sincos"


class BackwardSinCos(ActivationBackward):
    MAPPING = "activation_sincos"
    ACTIVATION = "sincos"


class ForwardMul(ParamlessForward):
    """y = x * factor (Znicz ForwardMul)."""

    MAPPING = "activation_mul"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.factor = float(kwargs.get("factor", 1.0))
        self.include_bias = False

    def apply(self, params, x):
        return x * self.factor

    def apply_numpy(self, params, x):
        return x * self.factor


class BackwardMul(GradientDescentBase):
    MAPPING = "activation_mul"

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("learning_rate", 0.0)
        super().__init__(workflow, **kwargs)
        self.factor = float(kwargs.get("factor", 1.0))

    def backward(self, params, x, y, err_output, n_valid=None):
        return err_output * self.factor, {}

    backward_numpy = backward
