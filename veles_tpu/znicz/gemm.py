"""Compensated blocked GEMM: the reference's PRECISION_LEVEL semantics,
as a Pallas TPU kernel.

Re-creation of /root/reference/ocl/matrix_multiplication_precise.cl
(:37-48 contract, :119-170 accumulators): the reference's GEMM offered
PRECISION_LEVEL 0 (plain summation), 1 (Kahan summation), 2 ("most
precise": 32 sorted partials) — trading ~2x speed for ~2 more correct
decimal digits on large common dims.

TPU redesign: scalar-loop Kahan cannot ride the MXU (the systolic array
owns the inner products), so compensation moves to the BLOCK level — the
K dimension is tiled, each tile's partial product comes out of the MXU
in f32, and the running accumulation of tiles into the output block is
compensated in VMEM:

- level 0: plain ``acc += p`` (same blocking, uncompensated — the
  baseline the tests compare against);
- level 1: Kahan (one compensation term per output element);
- level 2: Kahan-Babuška-Neumaier second order (Klein's doubly
  compensated summation, two carry terms) — the 32-partial analog.

Intra-tile error (bk-length MXU chains) remains — that part of the
reference guarantee is hardware-owned on TPU (f32 MXU accumulation);
cross-tile cancellation, which dominates for large K, is what the
compensation recovers.  ``jax.config`` keeps XLA's algebraic rewrites
away from the compensation expressions (XLA does not reassociate floats
by default).

The jnp/XLA fallback for remote-compile backends stays in
``backends.Device.PRECISION_LEVELS`` (the MXU pass-decomposition knob);
this kernel is the opt-in exact-summation path
(``root.common.engine.precise_gemm`` or ``All2All(precise_gemm=N)``).
"""

import functools

import jax
import jax.numpy as jnp


def _interpret_default():
    return jax.default_backend() != "tpu"


def _accumulate_plain(p, acc_ref, _c1_ref, _c2_ref):
    acc_ref[:] = acc_ref[:] + p


def _accumulate_kahan(p, acc_ref, c1_ref, _c2_ref):
    # Kahan-Babuška-Neumaier: the rounding error of every (acc + p) is
    # carried in c1.  (Classic Kahan drops its compensation whenever a
    # summand exceeds the accumulator — exactly the cross-tile
    # cancellation case this kernel exists for — so the Neumaier form
    # is the honest "PRECISION_LEVEL 1".)
    s, e = _two_sum(acc_ref[:], p)
    acc_ref[:] = s
    c1_ref[:] = c1_ref[:] + e


def _two_sum(a, b):
    """Knuth's exact TwoSum: a + b = s + e with e the rounding error."""
    s = a + b
    v = s - a
    e = (a - (s - v)) + (b - v)
    return s, e


def _accumulate_klein(p, acc_ref, c1_ref, c2_ref):
    # Doubly compensated (Kahan-Babuška-Neumaier 2nd order): the error
    # of the main sum cascades into c1, c1's own error into c2
    s, e = _two_sum(acc_ref[:], p)
    c1, e2 = _two_sum(c1_ref[:], e)
    acc_ref[:] = s
    c1_ref[:] = c1
    c2_ref[:] = c2_ref[:] + e2


_ACCUMULATORS = {0: _accumulate_plain, 1: _accumulate_kahan,
                 2: _accumulate_klein}


#: hand-picked tile sizes — the `precise_gemm` autotune site's default
DEFAULT_BLOCK_M = 128
DEFAULT_BLOCK_N = 128
DEFAULT_BLOCK_K = 256


def _matmul_impl(a, b, level, interpret, block_m=None, block_n=None,
                 block_k=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = _interpret_default()
    a = jnp.asarray(a, jnp.float32)
    b = jnp.asarray(b, jnp.float32)
    m, k = a.shape
    k2, n = b.shape
    if k != k2:
        raise ValueError("shape mismatch %s @ %s" % (a.shape, b.shape))
    if block_m is None or block_n is None or block_k is None:
        # unpinned tiles resolve through the tuning store (clean miss /
        # tuner off = the hand-picked defaults, exactly) — forward and
        # backward matmuls each resolve for their OWN (m, k, n) class
        from ..autotune import dispatch as _autotune
        from ..autotune.space import site as _site
        ctx = {"m": m, "k": k, "n": n, "level": int(level)}
        cfg, _ = _autotune.resolve(
            "precise_gemm", _site("precise_gemm").shape_class(ctx),
            default={"block_m": DEFAULT_BLOCK_M,
                     "block_n": DEFAULT_BLOCK_N,
                     "block_k": DEFAULT_BLOCK_K})
        block_m = block_m if block_m is not None else int(cfg["block_m"])
        block_n = block_n if block_n is not None else int(cfg["block_n"])
        block_k = block_k if block_k is not None else int(cfg["block_k"])
    bm, bn, bk = min(block_m, m), min(block_n, n), min(block_k, k)
    pad_m, pad_n, pad_k = (-m) % bm, (-n) % bn, (-k) % bk
    if pad_m or pad_k:
        a = jnp.pad(a, ((0, pad_m), (0, pad_k)))
    if pad_k or pad_n:
        b = jnp.pad(b, ((0, pad_k), (0, pad_n)))
    grid = (a.shape[0] // bm, b.shape[1] // bn, a.shape[1] // bk)
    accumulate = _ACCUMULATORS[int(level)]
    k_steps = grid[2]

    def kernel(a_ref, b_ref, o_ref, acc_ref, c1_ref, c2_ref):
        kk = pl.program_id(2)

        @pl.when(kk == 0)
        def _():
            acc_ref[:] = jnp.zeros_like(acc_ref)
            c1_ref[:] = jnp.zeros_like(c1_ref)
            c2_ref[:] = jnp.zeros_like(c2_ref)

        # HIGHEST = exact-f32 tile products (6-pass bf16 decomposition
        # on the MXU, plain f32 in interpret mode).  The reference's
        # levels all multiplied exact floats and differed only in the
        # SUMMATION (matrix_multiplication_precise.cl:37-48); default
        # precision here would drown the compensation in bf16 product
        # noise
        p = jnp.dot(a_ref[:], b_ref[:],
                    preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision.HIGHEST)
        accumulate(p, acc_ref, c1_ref, c2_ref)

        @pl.when(kk == k_steps - 1)
        def _():
            # fold the carries back in (zero for level 0)
            o_ref[:] = acc_ref[:] + (c1_ref[:] + c2_ref[:])

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
                  pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j))],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct(
            (a.shape[0], b.shape[1]), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)] * 3,
        # CompilerParams was TPUCompilerParams before jax 0.5
        compiler_params=getattr(pltpu, "CompilerParams",
                                getattr(pltpu, "TPUCompilerParams",
                                        None))(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(a, b)
    if pad_m or pad_n:
        out = out[:m, :n]
    return out


# -- quantized weight GEMM (ISSUE 18) -----------------------------------------
#
# Serving-side counterpart of the compensated path above: the weights
# are static at serve time, so they quantize ONCE (symmetric, one f32
# scale per output channel) and the kernel streams int8/fp8 bytes from
# HBM, upcasting each tile in VMEM and folding the channel scales into
# the output tile after the K loop — scaled accumulation, exact up to
# the weight quantization itself because per-output-channel scales
# factor out of the K contraction.

#: largest-magnitude finite value of float8_e4m3fn (the fp8 flavor
#: jaxlib exposes for storage): per-channel scales target it the way
#: int8 targets 127
_FP8_E4M3_MAX = 448.0


def fp8_dtype():
    """The jaxlib's storage fp8 dtype, or None when this jaxlib has
    none (callers gate the fp8 weight path on this)."""
    return getattr(jnp, "float8_e4m3fn", None)


def quantize_weight(w, dtype="int8"):
    """Symmetric per-output-channel quantization of a ``[K, N]`` weight.

    Returns ``(w_q, scales)``: ``w_q`` in ``dtype`` (``"int8"`` or
    ``"fp8"``), ``scales`` f32 ``[N]`` with ``scale[n] =
    max|w[:, n]| / qmax`` (1.0 for an all-zero column).  Because the
    scale is constant along K, ``x @ dequant(w_q)`` ==
    ``(x @ upcast(w_q)) * scales`` — which is what lets
    :func:`quantized_matmul` dequantize AFTER the accumulation.
    """
    w = jnp.asarray(w, jnp.float32)
    if w.ndim != 2:
        raise ValueError("quantize_weight wants [K, N], got %r"
                         % (w.shape,))
    amax = jnp.max(jnp.abs(w), axis=0)
    if dtype == "int8":
        scales = jnp.where(amax > 0, amax / 127.0, 1.0)
        q = jnp.clip(jnp.round(w / scales[None, :]), -127, 127)
        return q.astype(jnp.int8), scales.astype(jnp.float32)
    if dtype == "fp8":
        f8 = fp8_dtype()
        if f8 is None:
            raise ValueError(
                "this jaxlib exposes no float8 dtype; use dtype='int8'")
        scales = jnp.where(amax > 0, amax / _FP8_E4M3_MAX, 1.0)
        return (w / scales[None, :]).astype(f8), \
            scales.astype(jnp.float32)
    raise ValueError("unknown weight dtype %r (want 'int8'|'fp8')"
                     % (dtype,))


def quantized_matmul(a, w_q, scales, block_m=None, block_n=None,
                     block_k=None, interpret=None):
    """``a @ dequant(w_q)`` with the dequant inside the kernel.

    ``a``: f32 [M, K]; ``w_q``: int8/fp8 [K, N] with f32 ``scales``
    [N] from :func:`quantize_weight`.  The weight tiles cross HBM in
    their quantized width; each tile upcasts to f32 in VMEM for the
    MXU, the accumulator runs plain f32, and the per-channel scales
    multiply the finished output tile once after the K loop.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = _interpret_default()
    a = jnp.asarray(a, jnp.float32)
    m, k = a.shape
    k2, n = w_q.shape
    if k != k2:
        raise ValueError("shape mismatch %s @ %s" % (a.shape, w_q.shape))
    if scales.shape != (n,):
        raise ValueError("scales shape %r != (N,) == (%d,)"
                         % (scales.shape, n))
    bm = min(block_m or DEFAULT_BLOCK_M, m)
    bn = min(block_n or DEFAULT_BLOCK_N, n)
    bk = min(block_k or DEFAULT_BLOCK_K, k)
    pad_m, pad_n, pad_k = (-m) % bm, (-n) % bn, (-k) % bk
    if pad_m or pad_k:
        a = jnp.pad(a, ((0, pad_m), (0, pad_k)))
    if pad_k or pad_n:
        w_q = jnp.pad(w_q, ((0, pad_k), (0, pad_n)))
    s2 = jnp.pad(scales.astype(jnp.float32),
                 (0, pad_n))[None, :]              # [1, N] for blocking
    grid = (a.shape[0] // bm, w_q.shape[1] // bn, a.shape[1] // bk)
    k_steps = grid[2]

    def kernel(a_ref, b_ref, s_ref, o_ref, acc_ref):
        kk = pl.program_id(2)

        @pl.when(kk == 0)
        def _():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        p = jnp.dot(a_ref[:], b_ref[:].astype(jnp.float32),
                    preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision.HIGHEST)
        acc_ref[:] = acc_ref[:] + p

        @pl.when(kk == k_steps - 1)
        def _():
            o_ref[:] = acc_ref[:] * s_ref[0][None, :]

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
                  pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
                  pl.BlockSpec((1, bn), lambda i, j, kk: (0, j))],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct(
            (a.shape[0], w_q.shape[1]), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=getattr(pltpu, "CompilerParams",
                                getattr(pltpu, "TPUCompilerParams",
                                        None))(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(a, w_q, s2)
    if pad_m or pad_n:
        out = out[:m, :n]
    return out


def quantized_matmul_reference(a, w_q, scales, block_m=None,
                               block_n=None, block_k=None):
    """Pure-jnp oracle for :func:`quantized_matmul`, staged the way the
    kernel accumulates (K-tile-sequential partial products, scales
    folded after the loop) so parity tests can assert bitwise."""
    a = jnp.asarray(a, jnp.float32)
    m, k = a.shape
    bk = min(block_k or DEFAULT_BLOCK_K, k)
    pad_k = (-k) % bk
    if pad_k:
        a = jnp.pad(a, ((0, 0), (0, pad_k)))
        w_q = jnp.pad(w_q, ((0, pad_k), (0, 0)))
    acc = jnp.zeros((m, w_q.shape[1]), jnp.float32)
    for kk in range(a.shape[1] // bk):
        sl = slice(kk * bk, (kk + 1) * bk)
        acc = acc + jnp.dot(a[:, sl],
                            w_q[sl].astype(jnp.float32),
                            preferred_element_type=jnp.float32,
                            precision=jax.lax.Precision.HIGHEST)
    return acc * scales.astype(jnp.float32)[None, :]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def precise_matmul(a, b, level=1, interpret=None):
    """``a @ b`` with compensated cross-tile accumulation (see module
    docstring).  Differentiable: the backward matmuls run at the same
    precision level."""
    return _matmul_impl(a, b, level, interpret)


def _pm_fwd(a, b, level, interpret):
    return _matmul_impl(a, b, level, interpret), (a, b)


def _pm_bwd(level, interpret, res, g):
    a, b = res
    return (_matmul_impl(g, jnp.asarray(b, jnp.float32).T, level,
                         interpret),
            _matmul_impl(jnp.asarray(a, jnp.float32).T, g, level,
                         interpret))


precise_matmul.defvjp(_pm_fwd, _pm_bwd)
