"""WeightsRollback: restore the best weights when training degrades.

Re-creation of the Znicz rollback unit (SURVEY §2.9 "weight rollback
unit"): keep a copy of the parameters from the best validation epoch;
when validation fails to improve for ``improvement_limit`` consecutive
epochs, restore that copy (optionally also damping the learning rate via
the fused step's ``lr_scale``).
"""

from ..units import Unit


class WeightsRollback(Unit):
    MAPPING = "weights_rollback"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.view_group = "TRAINER"
        self.improvement_limit = int(kwargs.get("improvement_limit", 4))
        self.lr_damping = float(kwargs.get("lr_damping", 1.0))
        self.fused_step = None
        self.decision = None
        self.epoch_ended = None      # linked
        self.rollbacks = 0
        self._best_params_ = None
        self._best_opt_ = None

    def link_all(self, fused_step, decision, loader):
        self.fused_step = fused_step
        self.decision = decision
        self.link_attrs(loader, "epoch_ended")
        self.gate_skip = ~loader.epoch_ended
        return self

    def run(self):
        import jax.numpy as jnp
        step = self.fused_step
        if bool(self.decision.improved):
            # snapshot COPIES: the live buffers are donated next step
            self._best_params_ = [
                {k: jnp.array(v) for k, v in layer.items()}
                for layer in step._params_]
            self._best_opt_ = [
                {k: tuple(jnp.array(s) for s in v)
                 if isinstance(v, tuple) else jnp.array(v)
                 for k, v in layer.items()}
                for layer in step._opt_]
            return
        stale = getattr(self.decision, "epochs_without_improvement", 0)
        if self._best_params_ is not None and \
                stale and stale % self.improvement_limit == 0:
            step._params_ = [
                {k: jnp.array(v) for k, v in layer.items()}
                for layer in self._best_params_]
            step._opt_ = [
                {k: tuple(jnp.array(s) for s in v)
                 if isinstance(v, tuple) else jnp.array(v)
                 for k, v in layer.items()}
                for layer in self._best_opt_]
            # record the damping separately so a LearningRateAdjuster's
            # per-epoch assignment composes with it instead of erasing it
            step.lr_damping = getattr(step, "lr_damping", 1.0) * \
                self.lr_damping
            step.lr_scale = float(step.lr_scale) * self.lr_damping
            step.sync_weights()
            self.rollbacks += 1
