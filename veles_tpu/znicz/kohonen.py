"""Kohonen self-organizing map units (no-gradient trainer path).

Re-creation of the Znicz Kohonen family (absent submodule; model status
/root/reference/docs/source/manualrst_veles_algorithms.rst:71-85, unit
kwargs registry manualrst_veles_units_kwargs.jrst:73-78).  The reference
shipped OpenCL/numpy kernels for the winner search and the neighborhood
update; here both collapse into one jitted ``lax.scan`` over the
minibatch:

- winner search: ``argmin ||x - w||²`` computed as ``||w||² - 2·x@wᵀ``
  (one MXU matmul per sample batch instead of an O(N·F) distance kernel);
- neighborhood update: Gaussian over the 2-D grid coordinates,
  ``w += lr · exp(-d²/2σ²) · (x - w)`` — classic *online* SOM semantics
  (sample-sequential within the batch via ``lax.scan``), deterministic
  given the loader's shuffle order.

Learning rate and radius decay per epoch:  ``v = v0 · (vf/v0)^(t/T)``.
"""

import numpy

from ..memory import Array
from ..result_provider import IResultProvider
from ..units import Unit
from .. import loader as loader_mod


class KohonenBase(Unit):
    """Shared codebook holder: weights [rows*cols, n_input] on device."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.shape = tuple(kwargs.get("shape", (8, 8)))
        self.weights = Array()
        self.minibatch_data = None       # linked from loader
        self.minibatch_size = None

    @property
    def neurons_number(self):
        return int(numpy.prod(self.shape))

    def link_loader(self, loader):
        self.link_attrs(loader, "minibatch_data", "minibatch_size")
        return self


class KohonenForward(KohonenBase):
    """Winner lookup: maps each sample to its best-matching unit index.

    ``output`` holds the winner grid indices (flat) for the last served
    minibatch; ``distances`` the corresponding squared distances
    (quantization error per sample)."""

    MAPPING = "kohonen_forward"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.output = Array()
        self.distances = Array()

    def initialize(self, device=None, **kwargs):
        super().initialize(**kwargs)
        self.device = device
        import jax
        import jax.numpy as jnp

        @jax.jit
        def winners(w, x):
            # ||x-w||² = ||x||² - 2 x·w + ||w||²; ||x||² is constant in
            # the argmin, so one matmul + row norms suffice
            scores = (w * w).sum(axis=1)[None, :] - 2.0 * (x @ w.T)
            win = jnp.argmin(scores, axis=1)
            d = jnp.take_along_axis(scores, win[:, None], axis=1)[:, 0]
            d = d + (x * x).sum(axis=1)     # true squared distance
            return win.astype(jnp.int32), d
        self._winners_ = winners

    def run(self):
        win, d = self._winners_(self.weights.devmem,
                                self.minibatch_data.devmem)
        self.output.devmem = win
        self.distances.devmem = d


class KohonenTrainer(KohonenBase, IResultProvider):
    """Online SOM trainer: one jitted scan over the minibatch per run.

    kwargs: ``shape`` (grid rows, cols), ``sigma``/``sigma_final``
    (neighborhood radius schedule, defaults max(shape)/2 → 0.5),
    ``learning_rate``/``learning_rate_final`` (0.5 → 0.01), ``epochs``
    (schedule horizon, default decision's max_epochs), ``weights_stddev``.
    """

    MAPPING = "kohonen_trainer"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.view_group = "TRAINER"
        self.sigma = float(kwargs.get("sigma", max(self.shape) / 2.0))
        self.sigma_final = float(kwargs.get("sigma_final", 0.5))
        self.learning_rate = float(kwargs.get("learning_rate", 0.5))
        self.learning_rate_final = float(
            kwargs.get("learning_rate_final", 0.01))
        self.epochs = int(kwargs.get("epochs", 50))
        self.weights_stddev = float(kwargs.get("weights_stddev", 0.05))
        self.prng = kwargs.get("prng")
        self.epoch_number = None         # linked from loader
        self.last_minibatch = None
        self.minibatch_class = None
        # quantization error accumulator (device; flushed per epoch)
        self.qerror = Array(numpy.zeros(1, numpy.float64))
        self._epoch_samples = 0

    def link_loader(self, loader):
        super().link_loader(loader)
        self.link_attrs(loader, "epoch_number", "last_minibatch",
                        "minibatch_class")
        return self

    def initialize(self, device=None, **kwargs):
        super().initialize(**kwargs)
        self.device = device
        import jax
        import jax.numpy as jnp
        from jax import lax
        from ..prng import RandomGenerator

        n_input = int(numpy.prod(self.minibatch_data.shape[1:]))
        n = self.neurons_number
        if not self.weights:
            prng = self.prng or RandomGenerator().seed(1)
            self.weights.mem = prng.normal(
                0.0, self.weights_stddev, (n, n_input)).astype(numpy.float32)
        rows, cols = self.shape
        gy, gx = numpy.mgrid[0:rows, 0:cols]
        grid = numpy.stack([gy.ravel(), gx.ravel()], 1).astype(numpy.float32)
        grid_dev = jax.device_put(grid)

        def sample_update(w, x, lr, sigma):
            scores = (w * w).sum(axis=1) - 2.0 * (w @ x)
            win = jnp.argmin(scores)
            qe = scores[win] + (x * x).sum()
            dg = ((grid_dev - grid_dev[win]) ** 2).sum(axis=1)
            neigh = jnp.exp(-dg / (2.0 * sigma * sigma))
            w = w + lr * neigh[:, None] * (x[None, :] - w)
            return w, qe

        def train_batch(w, qacc, xb, size, lr, sigma):
            mask = jnp.arange(xb.shape[0]) < size

            def body(carry, inp):
                w, qacc = carry
                x, valid = inp
                w2, qe = sample_update(w, x, lr, sigma)
                w = jnp.where(valid, w2, w)
                qacc = qacc + jnp.where(valid, jnp.sqrt(
                    jnp.maximum(qe, 0.0)), 0.0)
                return (w, qacc), None
            (w, qacc), _ = lax.scan(body, (w, qacc), (xb, mask))
            return w, qacc

        self._train_batch_ = jax.jit(train_batch, donate_argnums=(0, 1))
        self._qacc_ = jnp.zeros((), jnp.float32)
        self._weights_dev_ = jnp.asarray(self.weights.map_read())

    def _schedule(self):
        t = min(self.epoch_number or 0, self.epochs) / max(self.epochs, 1)
        lr = self.learning_rate * (
            self.learning_rate_final / self.learning_rate) ** t
        sigma = self.sigma * (self.sigma_final / self.sigma) ** t
        return lr, sigma

    def run(self):
        if self.minibatch_class != loader_mod.TRAIN:
            return
        lr, sigma = self._schedule()
        xb = self.minibatch_data.devmem
        xb = xb.reshape(xb.shape[0], -1)
        self._weights_dev_, self._qacc_ = self._train_batch_(
            self._weights_dev_, self._qacc_, xb,
            int(self.minibatch_size), lr, sigma)
        self._epoch_samples += int(self.minibatch_size)
        if bool(self.last_minibatch):
            import jax
            self.qerror.map_write()[0] = (
                float(jax.device_get(self._qacc_)) /
                max(self._epoch_samples, 1))
            import jax.numpy as jnp
            self._qacc_ = jnp.zeros((), jnp.float32)
            self._epoch_samples = 0
            # publish a COPY: the live buffer is donated by the next
            # train step, which would leave readers of the public Array
            # holding a deleted device buffer
            self.weights.devmem = jnp.array(self._weights_dev_)

    def get_metric_values(self):
        return {"mean_quantization_error": float(self.qerror[0])}


class KohonenDecision(Unit, IResultProvider):
    """Epoch counter + quantization-error tracker for SOM training."""

    MAPPING = "kohonen_decision"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.view_group = "DECISION"
        self.max_epochs = int(kwargs.get("max_epochs", 50))
        self.silent = bool(kwargs.get("silent", False))
        from ..mutable import Bool
        self.complete = Bool(False)
        self.qerror = None               # linked from trainer
        self.epoch_number = None         # linked from loader
        self.epoch_ended = None
        self.qerror_history = []

    def link_loader(self, loader):
        self.link_attrs(loader, "epoch_number", "epoch_ended")
        return self

    def link_trainer(self, trainer):
        self.link_attrs(trainer, "qerror")
        return self

    def run(self):
        if not bool(self.epoch_ended):
            return
        qe = float(self.qerror[0])
        self.qerror_history.append(qe)
        if not self.silent:
            print("Epoch %d: mean quantization error %.4f" %
                  (self.epoch_number, qe))
        if self.epoch_number + 1 >= self.max_epochs:
            self.complete <<= True

    def get_metric_values(self):
        return {"final_quantization_error":
                self.qerror_history[-1] if self.qerror_history else None,
                "epochs": len(self.qerror_history)}
