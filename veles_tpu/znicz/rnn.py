"""Recurrent forward units: SimpleRNN and LSTM.

Re-creation of the Znicz RNN/LSTM units (reference model status: "built,
not fully tested" — manualrst_veles_algorithms.rst:115-143).  TPU-first:
the time recurrence is a ``lax.scan`` inside the pure ``apply`` (static
sequence length, XLA-compiled loop), so the units compose with the fused
trainer exactly like feed-forward layers — the generic vjp backward IS
backprop-through-time, no hand-written BPTT kernels.

Input: ``[batch, time, features]``; output: the last hidden state
``[batch, hidden]`` (``return_sequences=True`` → ``[batch, time,
hidden]``).
"""

import numpy

from .nn_units import ForwardBase
from .activations import get as get_activation


class SimpleRNN(ForwardBase):
    """h_t = tanh(x_t @ Wx + h_{t-1} @ Wh + b)."""

    MAPPING = "rnn"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.hidden = int(kwargs.get("hidden", 64))
        self.return_sequences = bool(kwargs.get("return_sequences", False))
        self.activation = get_activation(
            kwargs.get("activation", "tanh"))

    def output_shape_for(self, input_shape):
        b, t = input_shape[0], input_shape[1]
        if self.return_sequences:
            return (b, t, self.hidden)
        return (b, self.hidden)

    def init_params(self):
        f = int(numpy.prod(self.input_shape[2:]))
        self.fill_array(self.weights, (f + self.hidden, self.hidden),
                        self.weights_stddev, self.weights_filling)
        self.fill_array(self.bias, (self.hidden,), self.bias_stddev,
                        self.bias_filling)

    def apply(self, params, x):
        import jax.numpy as jnp
        from jax import lax
        w, b = params["weights"], params["bias"]
        f = x.shape[2] if x.ndim == 3 else int(
            numpy.prod(x.shape[2:]))
        x = x.reshape(x.shape[0], x.shape[1], f)
        wx, wh = w[:f], w[f:]
        h0 = jnp.zeros((x.shape[0], self.hidden), x.dtype)

        def step(h, xt):
            h = self.activation.fwd_jnp(xt @ wx + h @ wh + b)
            return h, h
        hT, hs = lax.scan(step, h0, jnp.swapaxes(x, 0, 1))
        if self.return_sequences:
            return jnp.swapaxes(hs, 0, 1)
        return hT

    def apply_numpy(self, params, x):
        w, b = params["weights"], params["bias"]
        f = x.shape[2]
        wx, wh = w[:f], w[f:]
        h = numpy.zeros((x.shape[0], self.hidden), x.dtype)
        hs = []
        for t in range(x.shape[1]):
            h = self.activation.fwd_np(x[:, t] @ wx + h @ wh + b)
            hs.append(h)
        return numpy.stack(hs, axis=1) if self.return_sequences else h


class LSTM(ForwardBase):
    """Standard LSTM cell scanned over time (i, f, g, o gates packed in
    one [f+h, 4h] weight matrix; forget-gate bias initialized to 1)."""

    MAPPING = "lstm"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.hidden = int(kwargs.get("hidden", 64))
        self.return_sequences = bool(kwargs.get("return_sequences", False))

    def output_shape_for(self, input_shape):
        b, t = input_shape[0], input_shape[1]
        if self.return_sequences:
            return (b, t, self.hidden)
        return (b, self.hidden)

    def init_params(self):
        f = int(numpy.prod(self.input_shape[2:]))
        H = self.hidden
        self.fill_array(self.weights, (f + H, 4 * H),
                        self.weights_stddev, self.weights_filling)
        bias = numpy.zeros(4 * H, numpy.float32)
        bias[H:2 * H] = 1.0  # forget-gate bias
        self.bias.mem = bias

    def _cell(self, xp, w, b, f_dim):
        H = self.hidden
        wx, wh = w[:f_dim], w[f_dim:]

        def step(carry, xt, sigmoid, tanh):
            h, c = carry
            z = xt @ wx + h @ wh + b
            i = sigmoid(z[:, :H])
            fg = sigmoid(z[:, H:2 * H])
            g = tanh(z[:, 2 * H:3 * H])
            o = sigmoid(z[:, 3 * H:])
            c = fg * c + i * g
            h = o * tanh(c)
            return (h, c), h
        return step

    def apply(self, params, x):
        import jax
        import jax.numpy as jnp
        from jax import lax
        w, b = params["weights"], params["bias"]
        f = x.shape[2] if x.ndim == 3 else int(numpy.prod(x.shape[2:]))
        x = x.reshape(x.shape[0], x.shape[1], f)
        step = self._cell(jnp, w, b, f)
        init = (jnp.zeros((x.shape[0], self.hidden), x.dtype),) * 2

        def body(carry, xt):
            return step(carry, xt, jax.nn.sigmoid, jnp.tanh)
        (hT, _cT), hs = lax.scan(body, init, jnp.swapaxes(x, 0, 1))
        if self.return_sequences:
            return jnp.swapaxes(hs, 0, 1)
        return hT

    def apply_numpy(self, params, x):
        w, b = params["weights"], params["bias"]
        f = x.shape[2]
        step = self._cell(numpy, w, b, f)

        def sigmoid(v):
            return 1.0 / (1.0 + numpy.exp(-v))
        carry = (numpy.zeros((x.shape[0], self.hidden), x.dtype),) * 2
        hs = []
        for t in range(x.shape[1]):
            carry, h = step(carry, x[:, t], sigmoid, numpy.tanh)
            hs.append(h)
        return numpy.stack(hs, axis=1) if self.return_sequences \
            else carry[0]


from .nn_units import GenericVJPBackward


class GDRNN(GenericVJPBackward):
    """BPTT for SimpleRNN via the generic vjp backward."""
    MAPPING = "rnn"


class GDLSTM(GenericVJPBackward):
    """BPTT for LSTM via the generic vjp backward."""
    MAPPING = "lstm"
