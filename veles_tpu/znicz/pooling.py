"""Pooling forward units.

Re-creation of ``veles.znicz.pooling`` (absent; SURVEY.md §2.9):
MaxPooling, AvgPooling, MaxAbsPooling, StochasticPooling(±Abs, ±Depooling).

TPU-first: ``lax.reduce_window`` — XLA's native windowed reduction —
whose autodiff emits ``SelectAndScatter`` for the backward.
:func:`fast_max_pool` is a measured-and-rejected alternative kept for
the record: a window-offset formulation with a hand-written VJP (int8
argmax plane forward, ky*kx predicated dilated pads backward) built on
the hypothesis that SelectAndScatter was the memory-bound backward
bottleneck; the round-4 interleaved on-chip A/B showed the OPPOSITE —
reduce_window trains AlexNet ~28 % faster end-to-end (7921 vs 6198
img/s median; docs/PERF.md) because XLA:TPU's select-and-scatter is
fine while the offset formulation's extra planes defeat fusion.  It
stays exported (grad-parity-tested against the reduce_window oracle)
for shapes where a recorded-argmax pooling is needed.

MaxAbsPooling keeps the *signed* value whose magnitude wins (the Znicz
semantic), built from two reductions.  Stochastic pooling samples a
window element with probability proportional to its magnitude (Zeiler &
Fergus), keyed by the unit's deterministic KeyTree so runs are
reproducible.
"""

import functools

import jax
import numpy

from ..prng.random_generator import KeyTree
from .nn_units import ParamlessForward
from .conv import _quad


def _offset_slice(arr, oy, ox, sy, sx, oh, ow):
    """The [b, oh, ow, c] plane of window element (oy, ox) across all
    (strided) window positions of a padded input."""
    return arr[:, oy:oy + (oh - 1) * sy + 1:sy,
               ox:ox + (ow - 1) * sx + 1:sx, :]


def _max_pool_core(x, window, strides, padding, use_abs, want_idx):
    import jax.numpy as jnp
    ky, kx = window
    sy, sx = strides
    (pt, pb), (pl, pr) = padding
    pad_val = 0.0 if use_abs else -numpy.inf
    xp_arr = jnp.pad(x, ((0, 0), (pt, pb), (pl, pr), (0, 0)),
                     constant_values=jnp.asarray(pad_val, x.dtype))
    hp, wp = xp_arr.shape[1], xp_arr.shape[2]
    oh, ow = (hp - ky) // sy + 1, (wp - kx) // sx + 1
    best = key = idx = None
    for k, (oy, ox) in enumerate(
            (oy, ox) for oy in range(ky) for ox in range(kx)):
        s = _offset_slice(xp_arr, oy, ox, sy, sx, oh, ow)
        cur = jnp.abs(s) if use_abs else s
        if best is None:
            best, key = s, cur
            idx = jnp.zeros(s.shape, jnp.int8) if want_idx else None
        else:
            better = cur > key  # strict: first max in window order wins
            best = jnp.where(better, s, best)
            key = jnp.where(better, cur, key)
            if want_idx:
                idx = jnp.where(better, jnp.int8(k), idx)
    return best, idx


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def fast_max_pool(x, window, strides, padding, use_abs):
    """Max (or max-|.|) pooling with a scatter-free backward; see the
    module docstring.  ``window``/``strides`` are (y, x) ints,
    ``padding`` is ((top, bottom), (left, right))."""
    best, _ = _max_pool_core(x, window, strides, padding, use_abs, False)
    return best


def _fast_max_pool_fwd(x, window, strides, padding, use_abs):
    best, idx = _max_pool_core(x, window, strides, padding, use_abs, True)
    return best, (idx, x.shape)


def _fast_max_pool_bwd(window, strides, padding, use_abs, res, g):
    import jax.numpy as jnp
    idx, xshape = res
    ky, kx = window
    sy, sx = strides
    (pt, pb), (pl, pr) = padding
    b, h, w, c = xshape
    hp, wp = h + pt + pb, w + pl + pr
    oh, ow = (hp - ky) // sy + 1, (wp - kx) // sx + 1
    dxp = jnp.zeros((b, hp, wp, c), g.dtype)
    for k, (oy, ox) in enumerate(
            (oy, ox) for oy in range(ky) for ox in range(kx)):
        contrib = jnp.where(idx == jnp.int8(k), g,
                            jnp.zeros((), g.dtype))
        dxp = _offset_slice(dxp.at, oy, ox, sy, sx, oh, ow).add(contrib)
    return (dxp[:, pt:pt + h, pl:pl + w, :],)


fast_max_pool.defvjp(_fast_max_pool_fwd, _fast_max_pool_bwd)


class PoolingBase(ParamlessForward):
    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.kx = kwargs["kx"]
        self.ky = kwargs["ky"]
        self.sliding = tuple(kwargs.get("sliding", (self.ky, self.kx)))
        self.padding = _quad(kwargs.get("padding", 0))
        self.include_bias = False

    def output_shape_for(self, input_shape):
        b, h, w, c = input_shape
        pt, pb, pl, pr = self.padding
        oh = (h + pt + pb - self.ky) // self.sliding[0] + 1
        ow = (w + pl + pr - self.kx) // self.sliding[1] + 1
        return (b, oh, ow, c)

    def _window_dims(self):
        return (1, self.ky, self.kx, 1)

    def _window_strides(self):
        return (1,) + self.sliding + (1,)

    def _window_padding(self):
        pt, pb, pl, pr = self.padding
        return ((0, 0), (pt, pb), (pl, pr), (0, 0))

    def numpy_windows(self, x):
        """Iterate (i, j, window[b, ky, kx, c]) host-side (numpy twin)."""
        pt, pb, pl, pr = self.padding
        xp = numpy.pad(x, ((0, 0), (pt, pb), (pl, pr), (0, 0)),
                       constant_values=self.PAD_VALUE)
        oh, ow = self.output_shape_for(x.shape)[1:3]
        sy, sx = self.sliding
        for i in range(oh):
            for j in range(ow):
                yield i, j, xp[:, i * sy:i * sy + self.ky,
                               j * sx:j * sx + self.kx, :]

    PAD_VALUE = 0.0


    def export_params(self):
        return {"kx": int(self.kx), "ky": int(self.ky),
                "padding": list(self.padding),
                "sliding": list(self.sliding)}


class MaxPooling(PoolingBase):
    """Max pooling via ``lax.reduce_window``, plus two opt-in layout
    experiments for the memory-bound pool region (round-5 hypotheses;
    docs/PERF.md ablation: max-pool machinery ~25 % of the AlexNet f32
    step):

    - ``pool_separable``: the 2-D window as two 1-D reduce_windows
      (rows then cols) — exact for max, reads ky+kx elements per output
      instead of ky*kx, and the backward becomes two smaller
      select-and-scatters (the first pass output is already
      row-decimated);
    - ``pool_bf16``: run the window (and therefore its backward select)
      on bfloat16 activations — halves the HBM bytes of the dominant
      pre-pool tensor; output upcast to the input dtype.  Numerics: max
      VALUES round to bf16 (~3 decimal digits) and near-ties may pick a
      different winner; opt-in only.

    Both default to ``root.common.engine.pool_separable`` /
    ``.pool_bf16`` (False) and compose."""

    MAPPING = "max_pooling"
    PAD_VALUE = -numpy.inf

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        from ..config import root
        eng = root.common.engine
        self.pool_separable = bool(kwargs.get(
            "pool_separable", eng.get("pool_separable", False)))
        self.pool_bf16 = bool(kwargs.get(
            "pool_bf16", eng.get("pool_bf16", False)))

    def apply(self, params, x):
        import jax.numpy as jnp
        from jax import lax
        dtype = x.dtype
        if self.pool_bf16:
            x = x.astype(jnp.bfloat16)
        if self.pool_separable:
            (pt, pb), (pl, pr) = self._window_padding()[1:3]
            sy, sx = self.sliding
            x = lax.reduce_window(
                x, -numpy.inf, lax.max, (1, self.ky, 1, 1),
                (1, sy, 1, 1), ((0, 0), (pt, pb), (0, 0), (0, 0)))
            x = lax.reduce_window(
                x, -numpy.inf, lax.max, (1, 1, self.kx, 1),
                (1, 1, sx, 1), ((0, 0), (0, 0), (pl, pr), (0, 0)))
        else:
            x = lax.reduce_window(
                x, -numpy.inf, lax.max, self._window_dims(),
                self._window_strides(), self._window_padding())
        return x.astype(dtype) if x.dtype != dtype else x

    def apply_numpy(self, params, x):
        out = numpy.empty(self.output_shape_for(x.shape), x.dtype)
        for i, j, win in self.numpy_windows(x):
            out[:, i, j, :] = win.max(axis=(1, 2))
        return out


class AvgPooling(PoolingBase):
    MAPPING = "avg_pooling"

    def apply(self, params, x):
        import jax.numpy as jnp
        from jax import lax
        s = lax.reduce_window(x, 0.0, lax.add, self._window_dims(),
                              self._window_strides(),
                              self._window_padding())
        # the in-bounds count per window is pure geometry — computing
        # it as reduce_window(ones) made XLA constant-fold a full-size
        # windowed reduction at COMPILE time (observed 45+ s of
        # slow_operation_alarm per stl10 compile); numpy at trace time
        # produces the same [1, oh, ow, 1] constant for free
        return s / jnp.asarray(self._window_counts(x.shape), x.dtype)

    def _window_counts(self, xshape):
        _, h, w, _ = xshape
        key = (h, w, self.ky, self.kx, self.sliding, self.padding)
        cached = getattr(self, "_counts_cache_", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        ones = numpy.ones((1, h, w, 1), numpy.float32)
        counts = numpy.empty(
            (1,) + self.output_shape_for((1, h, w, 1))[1:3] + (1,),
            numpy.float32)
        for i, j, win in self.numpy_windows(ones):
            counts[:, i, j, :] = win.sum(axis=(1, 2))
        self._counts_cache_ = (key, counts)
        return counts

    def apply_numpy(self, params, x):
        """Divides by the count of in-bounds elements per window (matching
        the jax path's ones-reduction), not by the full window size."""
        out = numpy.empty(self.output_shape_for(x.shape), x.dtype)
        counts = numpy.empty_like(out)
        for i, j, win in self.numpy_windows(x):
            out[:, i, j, :] = win.sum(axis=(1, 2))
        for i, j, win in self.numpy_windows(numpy.ones_like(x)):
            counts[:, i, j, :] = win.sum(axis=(1, 2))
        return out / counts


class MaxAbsPooling(PoolingBase):
    """Keeps the signed value with the largest magnitude (Znicz
    semantics)."""

    MAPPING = "maxabs_pooling"

    def apply(self, params, x):
        from jax import lax
        hi = lax.reduce_window(x, -numpy.inf, lax.max,
                               self._window_dims(), self._window_strides(),
                               self._window_padding())
        lo = lax.reduce_window(x, numpy.inf, lax.min,
                               self._window_dims(), self._window_strides(),
                               self._window_padding())
        import jax.numpy as jnp
        return jnp.where(jnp.abs(hi) >= jnp.abs(lo), hi, lo)

    def apply_numpy(self, params, x):
        out = numpy.empty(self.output_shape_for(x.shape), x.dtype)
        for i, j, win in self.numpy_windows(x):
            flat = win.reshape(win.shape[0], -1, win.shape[-1])
            idx = numpy.abs(flat).argmax(axis=1)
            out[:, i, j, :] = numpy.take_along_axis(
                flat, idx[:, None, :], axis=1)[:, 0, :]
        return out


class StochasticPoolingBase(PoolingBase):
    """Samples a window element ∝ its (abs) value at train time (the key
    arrives as an argument so jit never freezes the randomness); at eval
    time outputs the probability-weighted average (Zeiler & Fergus)."""

    hide_from_registry = True
    stochastic = True

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.key_tree = kwargs.get("key_tree") or KeyTree(
            kwargs.get("seed", 42))

    def _patches(self, x):
        """(b, oh, ow, ky*kx, c) patch tensor via jnp slicing."""
        import jax.numpy as jnp
        pt, pb, pl, pr = self.padding
        xp = jnp.pad(x, ((0, 0), (pt, pb), (pl, pr), (0, 0)))
        oh, ow = self.output_shape_for(x.shape)[1:3]
        sy, sx = self.sliding
        rows = []
        for dy in range(self.ky):
            for dx in range(self.kx):
                rows.append(xp[:, dy:dy + oh * sy:sy,
                               dx:dx + ow * sx:sx, :])
        return jnp.stack(rows, axis=3)

    ABS = True

    def _probs(self, p):
        import jax.numpy as jnp
        mag = jnp.abs(p) if self.ABS else jnp.maximum(p, 0.0)
        total = mag.sum(axis=3, keepdims=True)
        return jnp.where(total > 0, mag / jnp.maximum(total, 1e-30),
                         1.0 / p.shape[3])

    def apply(self, params, x):
        """Eval mode: probability-weighted average over the window."""
        p = self._patches(x)
        return (p * self._probs(p)).sum(axis=3)

    def apply_train(self, params, x, key):
        import jax
        import jax.numpy as jnp
        p = self._patches(x)                     # (b, oh, ow, k, c)
        logits = jnp.log(self._probs(p) + 1e-30)
        choice = jax.random.categorical(
            key, logits.transpose(0, 1, 2, 4, 3))  # (b, oh, ow, c)
        return jnp.take_along_axis(
            p, choice[:, :, :, None, :], axis=3)[:, :, :, 0, :]

    def apply_numpy(self, params, x):
        # the eval path is deterministic; the twin replays it on CPU
        return numpy.asarray(self.apply(params, x))


class StochasticPooling(StochasticPoolingBase):
    MAPPING = "stochastic_pooling"
    ABS = False


class StochasticAbsPooling(StochasticPoolingBase):
    MAPPING = "stochastic_abs_pooling"
    ABS = True


class StochasticPoolingDepooling(StochasticPooling):
    """Pools stochastically and immediately depools into the original
    shape (used by the Znicz conv autoencoders)."""

    MAPPING = "stochastic_pool_depool"

    def output_shape_for(self, input_shape):
        return tuple(input_shape)

    def apply(self, params, x):
        """Eval: keep the expected value in place (prob-weighted mask)."""
        p = self._patches(x)
        return self._scatter_back(p * self._probs(p), x)

    def apply_train(self, params, x, key):
        import jax
        import jax.numpy as jnp
        p = self._patches(x)
        choice = jax.random.categorical(
            key, jnp.log(self._probs(p) + 1e-30).transpose(0, 1, 2, 4, 3))
        mask = jax.nn.one_hot(choice, p.shape[3], axis=3, dtype=x.dtype)
        return self._scatter_back(p * mask, x)

    def _scatter_back(self, kept, x):
        # scatter windows back (non-overlapping sliding == window)
        b, oh, ow, _, c = kept.shape
        kept = kept.reshape(b, oh, ow, self.ky, self.kx, c)
        kept = kept.transpose(0, 1, 3, 2, 4, 5).reshape(
            b, oh * self.ky, ow * self.kx, c)
        return kept[:, :x.shape[1], :x.shape[2], :]


class StochasticAbsPoolingDepooling(StochasticPoolingDepooling):
    MAPPING = "stochastic_abs_pool_depool"
    ABS = True
