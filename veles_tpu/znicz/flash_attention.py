"""Flash attention as a Pallas TPU kernel pair (forward + backward).

The hand-kernel capability case the framework was missing (VERDICT r4
item 4): LRN and GEMM hand kernels lost to XLA fusion because XLA
already fuses memory-bound elementwise chains well — attention is the
op where a hand kernel wins on TPU, because the win is ALGORITHMIC:
``attention_reference`` (znicz/attention.py, parallel/ring.py:27)
materializes the [B, H, T, T] score matrix through HBM, while this
kernel streams K/V blocks through VMEM with the online-softmax
recurrence and never materializes T x T anywhere.  HBM traffic drops
from O(T^2) to O(T * D), so the advantage GROWS with sequence length —
the regime the long-context/ring-attention story targets.

VMEM stays O(block): every kernel walks K (or Q) blocks via a third
grid dimension — Pallas pipelines the block DMAs while the online
recurrence lives in VMEM scratch across the innermost grid steps (the
canonical TPU flash structure).  Nothing is sized by T, so T=32k+
compiles in the same footprint as T=1k.

Same layout as the oracle: q/k/v [B, T, H, D] -> out [B, T, H, D];
numerics match to f32 tolerance (asserted in
tests/test_flash_attention.py).  The backward is the standard two-pass
flash backward (dq pass over Q tiles, dk/dv pass over K tiles) driven
by the forward's saved logsumexp — no [T, T] in the backward either.

Wiring: ``MultiHeadAttention(use_pallas=True)`` (or the global
``root.common.engine.use_pallas``) routes single-device attention here;
shapes the kernel cannot tile (T with no block-divisor >= 32) fall back
to the oracle with a logged warning, so the knob is always safe.
"""

import functools
import logging
import math

import jax
import jax.numpy as jnp
from jax import lax


def _interpret():
    return jax.default_backend() != "tpu"


DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256
_MIN_BLOCK = 32         # >= f32 sublane tile; smallest worthwhile tile
_STAT_LANES = 128       # per-row stats (lse, delta) ride a full lane
                        # dim INSIDE the kernels: Mosaic requires block
                        # last-dims (8, 128) tileable, so a [BH, T] row
                        # vector can't be blocked (1, block_q).  The
                        # forward's lse OUTPUT does not pay the 128x
                        # broadcast in HBM though: when block_q divides
                        # into whole 128-lane rows the kernel emits a
                        # compact [BH, T//128, 128] block layout (the T
                        # axis folded into lanes, one f32 per row —
                        # 134 MB -> 1 MB at BH=8, T=32k) and only the
                        # backward's kernel-boundary broadcast
                        # materializes lanes, transiently.  Small-T
                        # fallback blocks (32/64) keep the broadcast
                        # layout.
_NEG_INF = float("-inf")
_warned_shapes = set()


def _blocks(t, block_q, block_k):
    """(bq, bk) dividing T, searching down from the requested sizes;
    None when no divisor >= _MIN_BLOCK exists."""
    def fit(want):
        cand = min(want, t)
        while cand >= _MIN_BLOCK:
            if t % cand == 0:
                return cand
            cand //= 2
        return None

    bq, bk = fit(block_q), fit(block_k)
    if bq is None or bk is None:
        return None
    return bq, bk


def flash_attention_supported(t, block_q=DEFAULT_BLOCK_Q,
                              block_k=DEFAULT_BLOCK_K):
    return _blocks(t, block_q, block_k) is not None


def _block_needed(iq, jk, block_q, block_k, window=None):
    """Causal: does Q block iq see any of K block jk?  (first key pos
    <= last query pos; with a sliding ``window``, also last key pos
    inside the band of the first query pos)"""
    vis = jk * block_k <= iq * block_q + block_q - 1
    if window is not None:
        # query i sees keys in (i - window, i]: block visible iff its
        # LAST key > FIRST query - window
        vis = jnp.logical_and(
            vis, jk * block_k + block_k - 1 > iq * block_q - window)
    return vis


def _mask_causal(s, iq, jk, block_q, block_k, window=None):
    rows = iq * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = jk * block_k + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    mask = cols > rows
    if window is not None:
        mask = jnp.logical_or(mask, cols <= rows - window)
    return jnp.where(mask, _NEG_INF, s)


# -- sliding-window band geometry --------------------------------------------
#
# With a window the kernels run a BANDED grid: the streamed axis only
# visits the blocks a pinned block can actually see, so compute AND
# block DMA are O(T * window) instead of O(T^2).  The streamed grid
# index j maps to a logical block via the band start; index_maps clip
# into range and the in-kernel predicate skips any overshoot.


def _kband_start(iq, block_q, block_k, window):
    """First K block visible to Q block iq (keys > iq*bq - window)."""
    return jnp.maximum(0, (iq * block_q - window + 1) // block_k)


def _kband_size(block_q, block_k, window):
    """K blocks any single Q block can see, worst case over phases."""
    return (block_q + window - 2) // block_k + 2


def _qband_start(jk, block_q, block_k):
    """First Q block that sees K block jk (causal: queries >= keys)."""
    return (jk * block_k) // block_q


def _qband_size(block_q, block_k, window):
    """Q blocks any single K block is visible to, worst case."""
    return (block_k + window - 2) // block_q + 2


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr,
                acc_scr, *, scale, causal, block_q, block_k,
                window=None, window_grid=None, compact_stats=False):
    from jax.experimental import pallas as pl

    iq, j = pl.program_id(1), pl.program_id(2)
    n_inner = pl.num_programs(2)
    # banded grid (window_grid set): j is an offset into the band;
    # window alone may also be set with a DENSE grid (band >= n_k),
    # where the mask enforces it
    jk = j if window_grid is None else _kband_start(
        iq, block_q, block_k, window_grid) + j

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(_block_needed(iq, jk, block_q, block_k, window)
             if causal else jk >= 0)
    def _step():
        q = q_ref[0].astype(jnp.float32) * scale       # [BQ, D]
        kb = k_ref[0].astype(jnp.float32)              # [BK, D]
        vb = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [BQ, BK]
        if causal:
            s = _mask_causal(s, iq, jk, block_q, block_k, window)
        m = m_scr[...]
        new_m = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # a fully-masked row keeps m at -inf: exp(-inf - -inf) must be
        # 0, not nan (same guard as parallel/ring.py:77)
        safe_m = jnp.where(jnp.isneginf(new_m), 0.0, new_m)
        alpha = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe_m))
        p = jnp.where(jnp.isneginf(s), 0.0, jnp.exp(s - safe_m))
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=-1,
                                                  keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = new_m

    @pl.when(j == n_inner - 1)
    def _finish():
        m, l = m_scr[...], l_scr[...]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[...] / safe_l).astype(o_ref.dtype)
        lse = jnp.where(jnp.isneginf(m), 0.0, m) + jnp.log(safe_l)
        if compact_stats:
            # fold the [BQ, 1] column into whole 128-lane rows: one f32
            # per query row in HBM instead of a 128x lane broadcast (a
            # single in-VMEM relayout per Q block — negligible next to
            # the saved HBM write traffic)
            lse_ref[0] = lse.reshape(block_q // _STAT_LANES, _STAT_LANES)
        else:
            lse_ref[0] = jnp.broadcast_to(lse, (block_q, _STAT_LANES))


def _struct(shape, dtype, vma):
    """ShapeDtypeStruct, with mesh-variance declared when the kernel
    runs inside a shard_map (ring flash attention) — check_vma requires
    pallas outputs to state their varying axes."""
    if vma is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(vma))


def _flash_fwd_bh(q, k, v, scale, causal, block_q, block_k, vma=None,
                  window=None):
    """Forward over [BH, T, D] operands; returns (out, lse[BH, T])."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, t, d = q.shape
    n_q, n_k = t // block_q, t // block_k
    if window is not None and _kband_size(block_q, block_k,
                                          window) >= n_k:
        window_grid = None  # band covers everything: dense grid,
        n_inner = n_k       # window enforced by the mask alone
    else:
        window_grid = window
        n_inner = n_k if window is None else _kband_size(
            block_q, block_k, window)
    # compact stats layout whenever each Q block covers whole 128-lane
    # rows (default 256/128 blocks do; the 32/64 fallbacks keep the
    # lane-broadcast layout) — see the _STAT_LANES note
    compact = block_q % _STAT_LANES == 0
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, window=window, window_grid=window_grid,
        compact_stats=compact)
    qspec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    if window_grid is None:
        k_index = lambda b, i, j: (b, j, 0)  # noqa: E731
    else:
        k_index = lambda b, i, j: (  # noqa: E731
            b, jnp.clip(_kband_start(i, block_q, block_k, window_grid)
                        + j, 0, n_k - 1), 0)
    kspec = pl.BlockSpec((1, block_k, d), k_index)
    if compact:
        lse_spec = pl.BlockSpec((1, block_q // _STAT_LANES, _STAT_LANES),
                                lambda b, i, j: (b, i, 0))
        lse_shape = (bh, t // _STAT_LANES, _STAT_LANES)
    else:
        lse_spec = pl.BlockSpec((1, block_q, _STAT_LANES),
                                lambda b, i, j: (b, i, 0))
        lse_shape = (bh, t, _STAT_LANES)
    out, lse = pl.pallas_call(
        kernel, grid=(bh, n_q, n_inner),
        in_specs=[qspec, kspec, kspec],
        out_specs=[qspec, lse_spec],
        out_shape=[_struct((bh, t, d), q.dtype, vma),
                   _struct(lse_shape, jnp.float32, vma)],
        scratch_shapes=[pltpu.VMEM((block_q, 1), jnp.float32),
                        pltpu.VMEM((block_q, 1), jnp.float32),
                        pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret())(q, k, v)
    # contiguous fold back to [BH, T] rows (free: a metadata reshape in
    # the compact layout, a lane slice otherwise)
    return out, (lse.reshape(bh, t) if compact else lse[:, :, 0])


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_scr, *, scale, causal, block_q, block_k,
               window=None, window_grid=None):
    from jax.experimental import pallas as pl

    iq, j = pl.program_id(1), pl.program_id(2)
    n_inner = pl.num_programs(2)
    jk = j if window_grid is None else _kband_start(
        iq, block_q, block_k, window_grid) + j

    @pl.when(j == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    @pl.when(_block_needed(iq, jk, block_q, block_k, window)
             if causal else jk >= 0)
    def _step():
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, :, 0:1]
        delta = delta_ref[0, :, 0:1]
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _mask_causal(s, iq, jk, block_q, block_k, window)
        p = jnp.where(jnp.isneginf(s), 0.0, jnp.exp(s - lse))
        dov = jax.lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [BQ, BK]
        ds = p * (dov - delta)
        dq_scr[...] = dq_scr[...] + jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(j == n_inner - 1)
    def _finish():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal,
                block_q, block_k, window=None, window_grid=None,
                n_q_total=None):
    from jax.experimental import pallas as pl

    jk, j = pl.program_id(1), pl.program_id(2)
    n_inner = pl.num_programs(2)
    iq = j if window_grid is None else _qband_start(
        jk, block_q, block_k) + j
    visible = (_block_needed(iq, jk, block_q, block_k, window)
               if causal else iq >= 0)
    if window_grid is not None:
        # the q band's top is NOT capped by causality (unlike the
        # fwd/dq k band): exclude overshoot past the last Q block
        visible = jnp.logical_and(visible, iq <= n_q_total - 1)

    @pl.when(j == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    @pl.when(visible)
    def _step():
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, :, 0:1]
        delta = delta_ref[0, :, 0:1]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _mask_causal(s, iq, jk, block_q, block_k, window)
        p = jnp.where(jnp.isneginf(s), 0.0, jnp.exp(s - lse))
        dv_scr[...] = dv_scr[...] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dov = jax.lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dov - delta)
        dk_scr[...] = dk_scr[...] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(j == n_inner - 1)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd_bh(q, k, v, out, lse, do, scale, causal, block_q,
                  block_k, vma=None, delta=None, window=None):
    """lse (and the optional precomputed delta) may arrive either as
    [BH, T] rows or already lane-broadcast [BH, T, _STAT_LANES] — the
    ring backward hoists the broadcast out of its per-hop loop."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, t, d = q.shape
    n_q, n_k = t // block_q, t // block_k
    if delta is None:
        # delta_i = sum_d do*out — tiny elementwise reduce; XLA fuses it
        delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                        axis=-1)                       # [BH, T]
    # stats enter the kernels lane-broadcast (see _STAT_LANES)
    if delta.ndim == 2:
        delta = jnp.broadcast_to(delta[..., None],
                                 (bh, t, _STAT_LANES))
    if lse.ndim == 2:
        lse = jnp.broadcast_to(lse[..., None], (bh, t, _STAT_LANES))
    # band geometry mirrors _flash_fwd_bh: banded grids only when they
    # actually shrink the streamed axis
    if window is not None and _kband_size(block_q, block_k,
                                          window) < n_k:
        wg_k, nk_inner = window, _kband_size(block_q, block_k, window)
    else:
        wg_k, nk_inner = None, n_k
    if window is not None and _qband_size(block_q, block_k,
                                          window) < n_q:
        wg_q, nq_inner = window, _qband_size(block_q, block_k, window)
    else:
        wg_q, nq_inner = None, n_q
    qspec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    qrow = pl.BlockSpec((1, block_q, _STAT_LANES),
                        lambda b, i, j: (b, i, 0))
    if wg_k is None:
        dq_k_index = lambda b, i, j: (b, j, 0)  # noqa: E731
    else:
        dq_k_index = lambda b, i, j: (  # noqa: E731
            b, jnp.clip(_kband_start(i, block_q, block_k, wg_k) + j,
                        0, n_k - 1), 0)
    kspec = pl.BlockSpec((1, block_k, d), dq_k_index)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          window=window, window_grid=wg_k),
        grid=(bh, n_q, nk_inner),
        in_specs=[qspec, kspec, kspec, qspec, qrow, qrow],
        out_specs=qspec,
        out_shape=_struct((bh, t, d), q.dtype, vma),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret())(q, k, v, do, lse, delta)
    # dk/dv pass: K block pinned per middle-grid step, Q streams inner
    if wg_q is None:
        dkv_q_index = lambda b, j, i: (b, i, 0)  # noqa: E731
    else:
        dkv_q_index = lambda b, j, i: (  # noqa: E731
            b, jnp.clip(_qband_start(j, block_q, block_k) + i,
                        0, n_q - 1), 0)
    kq_spec = pl.BlockSpec((1, block_q, d), dkv_q_index)
    kq_row = pl.BlockSpec((1, block_q, _STAT_LANES), dkv_q_index)
    kk_spec = pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          window=window, window_grid=wg_q,
                          n_q_total=n_q),
        grid=(bh, n_k, nq_inner),
        in_specs=[kq_spec, kk_spec, kk_spec, kq_spec, kq_row, kq_row],
        out_specs=[kk_spec, kk_spec],
        out_shape=[_struct((bh, t, d), k.dtype, vma),
                   _struct((bh, t, d), v.dtype, vma)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=_interpret())(q, k, v, do, lse, delta)
    return dq, dk, dv


def _to_bh(x):
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _from_bh(x, b, h):
    bh, t, d = x.shape
    return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _warn_fallback(t):
    if t >= 256 and t not in _warned_shapes:
        _warned_shapes.add(t)
        logging.getLogger("flash_attention").warning(
            "T=%d has no block divisor >= %d: falling back to the XLA "
            "oracle, which materializes the [T, T] scores (pad T to a "
            "multiple of %d to engage the flash kernel)",
            t, _MIN_BLOCK, _MIN_BLOCK)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention(q, k, v, causal, scale, block_q, block_k,
                     window):
    out, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k,
                        window)
    return out


def flash_attention(q, k, v, causal=False, scale=None, block_q=None,
                    block_k=None, window=None):
    """Flash attention, [B, T, H, D] — drop-in for
    ``attention_reference`` (falls back to it, with a logged warning,
    when T can't be tiled).  ``window`` (requires ``causal``):
    sliding-window attention — position i sees keys in
    (i - window, i]; off-band blocks skip their MXU work entirely.

    ``block_q``/``block_k`` default to the measured winner for this
    (T, D, device, versions) when a tuning record exists (autotune
    sites ``flash_attention`` / ``window_attention``), else the
    hand-picked :data:`DEFAULT_BLOCK_Q`/:data:`DEFAULT_BLOCK_K`;
    explicit values always win.  Resolution happens at trace time
    (shapes are static), outside the custom-vjp boundary."""
    if block_q is None or block_k is None:
        from ..autotune import dispatch as _autotune
        site = "window_attention" if window is not None \
            else "flash_attention"
        ctx = {"t": q.shape[1], "d": q.shape[3], "causal": causal}
        if window is not None:
            ctx["window"] = window
        from ..autotune.space import site as _site
        cfg, _ = _autotune.resolve(
            site, _site(site).shape_class(ctx),
            default={"block_q": DEFAULT_BLOCK_Q,
                     "block_k": DEFAULT_BLOCK_K})
        block_q = block_q if block_q is not None else int(cfg["block_q"])
        block_k = block_k if block_k is not None else int(cfg["block_k"])
    return _flash_attention(q, k, v, causal, scale, block_q, block_k,
                            window)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, window=None):
    from ..parallel.ring import attention_reference
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    if window is not None and window < 1:
        raise ValueError("window must be >= 1, got %r" % (window,))
    b, t, h, d = q.shape
    blocks = _blocks(t, block_q, block_k)
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if blocks is None:
        _warn_fallback(t)
        out = attention_reference(q, k, v, causal=causal, scale=scale,
                                  window=window)
        return out, (q, k, v, out, None)
    bq, bk = blocks
    out_bh, lse = _flash_fwd_bh(_to_bh(q), _to_bh(k), _to_bh(v),
                                scale, causal, bq, bk, window=window)
    out = _from_bh(out_bh, b, h)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, window, res, g):
    from ..parallel.ring import attention_reference
    q, k, v, out, lse = res
    b, t, h, d = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if lse is None:  # untileable shape took the oracle path forward
        _, vjp = jax.vjp(
            lambda q, k, v: attention_reference(q, k, v, causal=causal,
                                                scale=scale,
                                                window=window), q, k, v)
        return vjp(g)
    bq, bk = _blocks(t, block_q, block_k)
    dq, dk, dv = _flash_bwd_bh(
        _to_bh(q), _to_bh(k), _to_bh(v), _to_bh(out), lse, _to_bh(g),
        scale, causal, bq, bk, window=window)
    return (_from_bh(dq, b, h), _from_bh(dk, b, h), _from_bh(dv, b, h))


_flash_attention.defvjp(_flash_fwd, _flash_bwd)
