"""Restricted Boltzmann Machine: CD-1 trainer (no-gradient path).

Re-creation of the Znicz RBM units (reference model status: "units
developed for NUMPY, workflow created but not tested" —
/root/reference/docs/source/manualrst_veles_algorithms.rst:103-110).
TPU-first: one jitted contrastive-divergence step per minibatch —
sample h|v, reconstruct v'|h, resample h'|v', update
W += lr/B * (v·h - v'·h') — with the Bernoulli draws keyed per step for
determinism, and the reconstruction error accumulated on device.
"""

import numpy

from ..memory import Array
from ..result_provider import IResultProvider
from ..units import Unit
from .. import loader as loader_mod


class RBMTrainer(Unit, IResultProvider):
    """Binary-binary RBM trained with CD-1."""

    MAPPING = "rbm_trainer"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.view_group = "TRAINER"
        self.n_hidden = int(kwargs.get("n_hidden", 64))
        self.learning_rate = float(kwargs.get("learning_rate", 0.1))
        self.weights_stddev = float(kwargs.get("weights_stddev", 0.01))
        self.prng = kwargs.get("prng")
        self.weights = Array()       # [n_visible, n_hidden]
        self.vbias = Array()
        self.hbias = Array()
        self.minibatch_data = None   # linked
        self.minibatch_size = None
        self.minibatch_class = None
        self.last_minibatch = None
        self.epoch_number = None
        self.recon_error = Array(numpy.zeros(1, numpy.float64))
        self._seed_counter = int(kwargs.get("seed", 11)) % 0x7FFF0000
        self._epoch_samples = 0

    def link_loader(self, loader):
        self.link_attrs(loader, "minibatch_data", "minibatch_size",
                        "minibatch_class", "last_minibatch",
                        "epoch_number")
        return self

    def initialize(self, device=None, **kwargs):
        super().initialize(**kwargs)
        self.device = device
        import jax
        import jax.numpy as jnp
        from ..prng import RandomGenerator

        n_visible = int(numpy.prod(self.minibatch_data.shape[1:]))
        if not self.weights:
            prng = self.prng or RandomGenerator().seed(2)
            self.weights.mem = prng.normal(
                0.0, self.weights_stddev,
                (n_visible, self.n_hidden)).astype(numpy.float32)
            self.vbias.mem = numpy.zeros(n_visible, numpy.float32)
            self.hbias.mem = numpy.zeros(self.n_hidden, numpy.float32)

        lr = self.learning_rate

        def cd1(w, vb, hb, eacc, v, mask, seed):
            key = jax.random.PRNGKey(seed)
            kh, kv = jax.random.split(key)
            B = v.shape[0]
            ph = jax.nn.sigmoid(v @ w + hb)
            h = (jax.random.uniform(kh, ph.shape) < ph).astype(v.dtype)
            pv = jax.nn.sigmoid(h @ w.T + vb)
            # mean-field reconstruction (standard CD-1: probabilities for
            # the visible reconstruction, resampled hidden probs)
            ph2 = jax.nn.sigmoid(pv @ w + hb)
            m = mask[:, None]
            nv = jnp.maximum(mask.sum(), 1.0)
            dw = ((v * m).T @ ph - (pv * m).T @ ph2) / nv
            dvb = ((v - pv) * m).sum(axis=0) / nv
            dhb = ((ph - ph2) * m).sum(axis=0) / nv
            err = (((v - pv) ** 2) * m).sum() / nv
            return (w + lr * dw, vb + lr * dvb, hb + lr * dhb,
                    eacc + err * mask.sum())

        self._cd1_ = jax.jit(cd1, donate_argnums=(0, 1, 2, 3))
        self._w_ = jnp.asarray(self.weights.map_read())
        self._vb_ = jnp.asarray(self.vbias.map_read())
        self._hb_ = jnp.asarray(self.hbias.map_read())
        self._eacc_ = jnp.zeros((), jnp.float32)

    def run(self):
        if self.minibatch_class != loader_mod.TRAIN:
            return
        import jax.numpy as jnp
        v = self.minibatch_data.devmem
        v = v.reshape(v.shape[0], -1)
        size = int(self.minibatch_size)
        mask = (jnp.arange(v.shape[0]) < size).astype(v.dtype)
        self._seed_counter = (self._seed_counter + 1) % 0x7FFF0000
        (self._w_, self._vb_, self._hb_, self._eacc_) = self._cd1_(
            self._w_, self._vb_, self._hb_, self._eacc_, v, mask,
            self._seed_counter)
        self._epoch_samples += size
        if bool(self.last_minibatch):
            import jax
            self.recon_error.map_write()[0] = (
                float(jax.device_get(self._eacc_)) /
                max(self._epoch_samples, 1))
            self._eacc_ = jnp.zeros((), jnp.float32)
            self._epoch_samples = 0
            self.weights.devmem = jnp.array(self._w_)
            self.vbias.devmem = jnp.array(self._vb_)
            self.hbias.devmem = jnp.array(self._hb_)

    def get_metric_values(self):
        return {"reconstruction_error": float(self.recon_error[0])}
