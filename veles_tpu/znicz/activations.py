"""Activation functions shared by all2all/conv forwards and the activation
units.

Semantics follow the Znicz kernel conventions (reconstructed; the submodule
is absent — SURVEY.md §2.9):

- ``tanh``: LeCun-scaled ``1.7159 * tanh(0.6666 * x)``;
- ``relu``: smooth ``log(1 + exp(x))`` (Znicz's "RELU" is softplus);
- ``strict_relu``: ``max(0, x)``;
- ``sigmoid``: logistic;
- plus the activation-unit extras log/tanhlog/sincos/mul.

Each entry is ``(forward, derivative_from_output_and_input)``; derivatives
take ``(y, x)`` because several Znicz backward kernels use the *output*
(cheaper on-device: no need to keep x for tanh/sigmoid).
"""

import numpy


def _np_softplus(x):
    return numpy.log1p(numpy.exp(-numpy.abs(x))) + numpy.maximum(x, 0)


class Activation:
    """One activation: jnp + numpy forward, derivative for backprop."""

    def __init__(self, name, fwd_jnp, fwd_np, deriv_jnp, deriv_np):
        self.name = name
        self.fwd_jnp = fwd_jnp
        self.fwd_np = fwd_np
        self.deriv_jnp = deriv_jnp
        self.deriv_np = deriv_np

    def __reduce__(self):
        # pickles by name (the lambdas are module-level table entries)
        return (get, (self.name,))


def _make_table():
    import jax.numpy as jnp
    import jax
    A, B = 1.7159, 0.6666

    return {
        "linear": Activation(
            "linear",
            lambda x: x, lambda x: x,
            lambda y, x: jnp.ones_like(y), lambda y, x: numpy.ones_like(y)),
        "tanh": Activation(
            "tanh",
            lambda x: A * jnp.tanh(B * x),
            lambda x: A * numpy.tanh(B * x),
            # dy/dx = A*B*(1 - tanh^2) = B/A * (A^2 - y^2)
            lambda y, x: (y * y) * (-B / A) + A * B,
            lambda y, x: (y * y) * (-B / A) + A * B),
        "sigmoid": Activation(
            "sigmoid",
            lambda x: jax.nn.sigmoid(x),
            lambda x: 1.0 / (1.0 + numpy.exp(-x)),
            lambda y, x: y * (1.0 - y),
            lambda y, x: y * (1.0 - y)),
        "relu": Activation(
            "relu",
            lambda x: jnp.logaddexp(x, 0.0),
            _np_softplus,
            # y = log(1+e^x)  =>  dy/dx = 1 - e^-y
            lambda y, x: 1.0 - jnp.exp(-y),
            lambda y, x: 1.0 - numpy.exp(-y)),
        "strict_relu": Activation(
            "strict_relu",
            lambda x: jnp.maximum(x, 0.0),
            lambda x: numpy.maximum(x, 0.0),
            lambda y, x: (y > 0).astype(y.dtype),
            lambda y, x: (y > 0).astype(y.dtype)),
        "log": Activation(
            "log",
            lambda x: jnp.log(x + jnp.sqrt(x * x + 1.0)),
            lambda x: numpy.log(x + numpy.sqrt(x * x + 1.0)),
            lambda y, x: 1.0 / jnp.sqrt(x * x + 1.0),
            lambda y, x: 1.0 / numpy.sqrt(x * x + 1.0)),
        "tanhlog": Activation(
            "tanhlog",
            lambda x: jnp.where(jnp.abs(x) <= 15.0 / B,
                                A * jnp.tanh(B * x),
                                jnp.sign(x) * (jnp.log(jnp.abs(x) * B) / B +
                                               A * jnp.tanh(15.0))),
            lambda x: numpy.where(numpy.abs(x) <= 15.0 / B,
                                  A * numpy.tanh(B * x),
                                  numpy.sign(x) *
                                  (numpy.log(numpy.abs(x) * B) / B +
                                   A * numpy.tanh(15.0))),
            lambda y, x: jnp.where(jnp.abs(x) <= 15.0 / B,
                                   A * B / jnp.cosh(B * x) ** 2,
                                   1.0 / (B * jnp.abs(x))),
            lambda y, x: numpy.where(numpy.abs(x) <= 15.0 / B,
                                     A * B / numpy.cosh(B * x) ** 2,
                                     1.0 / (B * numpy.abs(x)))),
        "sincos": Activation(
            "sincos",
            lambda x: jnp.where(
                jnp.arange(x.shape[-1]) % 2 == 1, jnp.sin(x), jnp.cos(x)),
            lambda x: numpy.where(
                numpy.arange(x.shape[-1]) % 2 == 1,
                numpy.sin(x), numpy.cos(x)),
            lambda y, x: jnp.where(
                jnp.arange(x.shape[-1]) % 2 == 1, jnp.cos(x), -jnp.sin(x)),
            lambda y, x: numpy.where(
                numpy.arange(x.shape[-1]) % 2 == 1,
                numpy.cos(x), -numpy.sin(x))),
    }


_table = None


def get(name):
    global _table
    if _table is None:
        _table = _make_table()
    return _table[name]
