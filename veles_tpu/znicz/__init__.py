"""znicz: the neural-network unit library.

TPU-native re-creation of the (absent) veles.znicz submodule — the layer
inventory reconstructed in SURVEY.md §2.9 from
/root/reference/docs/source/manualrst_veles_workflow_parameters.rst:469-504
and manualrst_veles_algorithms.rst.  Forward/backward unit pairs over
JAX/XLA: every forward exposes a *pure* ``apply(params, x)`` used both by
its own jitted graph-mode kernel and by the fused single-step trainer that
StandardWorkflow builds (SURVEY.md §7: the hot loop collapses into one
jitted, donated step function).
"""

from . import activations                            # noqa: F401
from .nn_units import ForwardBase, GradientDescentBase  # noqa: F401
from .all2all import (All2All, All2AllTanh, All2AllSigmoid, All2AllRELU,
                      All2AllStrictRELU, All2AllSoftmax,
                      ResizableAll2All)                  # noqa: F401
from .gd import (GradientDescent, GDTanh, GDSigmoid, GDRELU,
                 GDStrictRELU, GDSoftmax, RPropAll2All)  # noqa: F401
from .evaluator import EvaluatorSoftmax, EvaluatorMSE    # noqa: F401
from .decision import (DecisionGD, DecisionMSE,
                       TrivialDecision)                  # noqa: F401
from .conv import (Conv, ConvTanh, ConvSigmoid, ConvRELU,
                   ConvStrictRELU)                       # noqa: F401
from .gd_conv import (GradientDescentConv, GDTanhConv, GDSigmoidConv,
                      GDRELUConv, GDStrictRELUConv)      # noqa: F401
from .pooling import (MaxPooling, AvgPooling, MaxAbsPooling,
                      StochasticPooling, StochasticAbsPooling,
                      StochasticPoolingDepooling,
                      StochasticAbsPoolingDepooling)     # noqa: F401
from .gd_pooling import (GDMaxPooling, GDAvgPooling,
                         GDMaxAbsPooling)                # noqa: F401
from .dropout import DropoutForward, DropoutBackward     # noqa: F401
from .lrn import (LRNormalizerForward,
                  LRNormalizerBackward)                  # noqa: F401
from . import activation                                 # noqa: F401
from .misc_units import (Cutter, GDCutter, ChannelSplitter,
                         ChannelMerger, ZeroFiller, Deconv, GDDeconv,
                         Depooling)                      # noqa: F401
from .attention import (MultiHeadAttention,
                        GDMultiHeadAttention)            # noqa: F401
from . import (image_saver, kohonen, lr_adjust, rbm,   # noqa: F401,E402
               rnn, rollback)
