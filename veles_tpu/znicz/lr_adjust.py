"""Learning-rate adjusters (reference znicz lr_adjust family).

Policies compute a multiplier over the configured base rates as a
function of the epoch; the fused step consumes it as the DYNAMIC
``lr_scale`` argument (no retrace per change), and graph-mode GD units
apply their rates eagerly, so the adjuster mutates them directly.

Policies (reference Caffe-style set):
- ``exp``:    scale = gamma^epoch
- ``step``:   scale = gamma^(epoch // step)
- ``inv``:    scale = (1 + gamma*epoch)^(-power)
- ``arbitrary``: explicit [(epoch, scale), ...] step points
"""

from ..units import Unit
from .. import loader as loader_mod


def make_policy(name, **kwargs):
    gamma = float(kwargs.get("gamma", 0.9))
    if name == "exp":
        return lambda epoch: gamma ** epoch
    if name == "step":
        step = int(kwargs.get("step", 10))
        return lambda epoch: gamma ** (epoch // step)
    if name == "inv":
        power = float(kwargs.get("power", 0.75))
        return lambda epoch: (1.0 + gamma * epoch) ** -power
    if name == "arbitrary":
        points = sorted(kwargs["points"])  # [(epoch, scale), ...]

        def arbitrary(epoch):
            scale = 1.0
            for at, value in points:
                if epoch >= at:
                    scale = value
            return scale
        return arbitrary
    raise ValueError("unknown lr policy %r" % name)


class LearningRateAdjuster(Unit):
    """Applies a schedule once per epoch.

    Wire: ``link_from(decision)``, ``link_loader(loader)``, and either
    ``link_fused(fused_step)`` or ``link_gds(*gd_units)`` (graph mode).
    """

    MAPPING = "lr_adjuster"

    def __init__(self, workflow, policy="exp", **kwargs):
        super().__init__(workflow, **kwargs)
        self.view_group = "TRAINER"
        self.policy_name = policy
        self.policy_kwargs = dict(kwargs)
        self.policy_kwargs.pop("name", None)
        # built once: a bad policy name/points fails at construction,
        # not a full epoch later
        self._policy_ = make_policy(policy, **self.policy_kwargs)
        self.epoch_ended = None      # linked
        self.epoch_number = None
        self.fused_step = None
        self.gds = []
        self._base_rates = None

    def init_unpickled(self):
        super().init_unpickled()
        # also invoked mid-__init__ (before our attributes exist): only
        # rebuild the callable on a real unpickle
        name = self.__dict__.get("policy_name")
        if name is not None:
            self._policy_ = make_policy(name, **self.policy_kwargs)

    def link_loader(self, loader):
        self.link_attrs(loader, "epoch_ended", "epoch_number")
        self.gate_skip = ~loader.epoch_ended
        return self

    def link_fused(self, fused_step):
        self.fused_step = fused_step
        return self

    def link_gds(self, *gds):
        self.gds = list(gds)
        self._base_rates = [(gd.learning_rate, gd.learning_rate_bias)
                            for gd in gds]
        return self

    def scale_for(self, epoch):
        return self._policy_(epoch)

    def run(self):
        # schedule for the NEXT epoch (this runs at the end of one)
        scale = self.scale_for(int(self.epoch_number) + 1)
        if self.fused_step is not None:
            # compose with any accumulated damping (WeightsRollback) —
            # an absolute assignment would silently undo it
            damping = getattr(self.fused_step, "lr_damping", 1.0)
            self.fused_step.lr_scale = float(scale * damping)
        for gd, (base_w, base_b) in zip(self.gds, self._base_rates or ()):
            gd.learning_rate = base_w * scale
            gd.learning_rate_bias = base_b * scale
