"""InputJoiner: concatenate N input Arrays along the feature axis.

Re-creation of /root/reference/veles/input_joiner.py:49 (+ the templated
``join`` kernel, ocl/join.jcl): the reference generated an OpenCL kernel
per input count; here one jitted ``jnp.concatenate`` covers every case
and XLA fuses it with the producers.
"""

import numpy

from .memory import Array
from .units import Unit


class InputJoiner(Unit):
    """``output = concat(inputs..., axis=-1)`` on device.

    Link inputs with ``link_inputs(unit_a, "output", unit_b, "output")``
    or assign ``input_<i>`` attributes directly."""

    MAPPING = "input_joiner"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.output = Array()
        self.num_inputs = 0

    def link_inputs(self, *unit_attr_pairs):
        """(unit, attr) pairs in join order."""
        for unit, attr in unit_attr_pairs:
            name = "input_%d" % self.num_inputs
            self.link_attrs(unit, (name, attr))
            self.num_inputs += 1
        return self

    def initialize(self, device=None, **kwargs):
        super().initialize(**kwargs)
        self.device = device
        import jax
        import jax.numpy as jnp

        @jax.jit
        def join(inputs):
            flat = [x.reshape(x.shape[0], -1) for x in inputs]
            return jnp.concatenate(flat, axis=-1)
        self._join_ = join
        # preallocate output so downstream units can size themselves at
        # initialize (the ForwardBase convention): rows from the first
        # input, width = sum of flattened feature widths
        shapes = []
        for i in range(self.num_inputs):
            v = getattr(self, "input_%d" % i)
            shape = v.shape if isinstance(v, Array) else numpy.shape(v)
            if not shape:
                shapes = None
                break
            shapes.append(shape)
        if shapes and not self.output:
            width = sum(int(numpy.prod(s[1:])) for s in shapes)
            self.output.reset(numpy.zeros((shapes[0][0], width),
                                          numpy.float32))

    def _value(self, i):
        v = getattr(self, "input_%d" % i)
        return v.devmem if isinstance(v, Array) else v

    def run(self):
        inputs = [self._value(i) for i in range(self.num_inputs)]
        if self.device is not None and self.device.exists:
            self.output.devmem = self._join_(tuple(inputs))
        else:
            flat = [numpy.asarray(x).reshape(len(x), -1) for x in inputs]
            self.output.mem = numpy.concatenate(flat, axis=-1)

    def make_trace(self):
        """Join face: the same reshape+concatenate the jitted ``_join_``
        runs, composed into the surrounding region (XLA fuses it with
        both producers and the consumer)."""
        from .graphcomp.faces import NoFace, TraceFace
        if not self.num_inputs:
            return NoFace("no inputs linked")
        if self.device is None or not self.device.exists:
            return NoFace("numpy backend (no jitted path)")
        names = tuple("input_%d" % i for i in range(self.num_inputs))

        def fn(state_in, inputs, statics):
            import jax.numpy as jnp
            flat = [inputs[n].reshape(inputs[n].shape[0], -1)
                    for n in names]
            return {}, {"output": jnp.concatenate(flat, axis=-1)}
        return TraceFace(self, fn, inputs=names, outputs=("output",))
