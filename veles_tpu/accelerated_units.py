"""Accelerated units: graph nodes whose compute is a jitted JAX function.

TPU-native re-design of /root/reference/veles/accelerated_units.py
(AcceleratedUnit :130 — per-backend init/run dispatch, Jinja2 kernel source
generation :509-565, tar.gz binary cache :605-673; AcceleratedWorkflow :827).

The reference compiles `.cl`/`.cu` sources per device and dispatches
`ocl_run`/`cuda_run`/`numpy_run`.  Here the "kernel" is a **pure function**
over arrays; `tpu_init` jits it (XLA's persistent compilation cache replaces
the tar.gz binary cache), `numpy_run` stays as the parity twin the test
strategy is built on (reference tests/accelerated_test.py:79).  The method
resolution mirrors the reference's ``assign_backend_methods`` trick
(backends.py:244-262): `initialize` binds `_backend_run_` to `tpu_run` or
`numpy_run` depending on the Device.

The `--sync-run` equivalent (`root.common.engine.sync_run`) calls
``block_until_ready`` after every unit for honest per-unit timings
(reference accelerated_units.py:292-295).
"""

import numpy

from .backends import Device, NumpyDevice
from .config import root
from .memory import Array
from .units import Unit


class AcceleratedUnit(Unit):
    """A unit with a jitted device path and a numpy parity path.

    Subclasses implement:

    - ``kernel(self, *arrays) -> arrays`` — a **pure** function of jax arrays
      (closed over static config only), jitted once at initialize;
    - ``numpy_run(self)`` — the host twin mutating Arrays in place;
    - optionally ``tpu_run(self)`` when the default "gather inputs → kernel →
      scatter outputs" protocol does not fit.

    Declare device I/O with ``self.device_inputs = ["input", ...]`` and
    ``self.device_outputs = ["output", ...]`` (attribute names holding
    :class:`~veles_tpu.memory.Array`).
    """

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.device = None
        self.device_inputs = []
        self.device_outputs = []
        self.intermediates = []  # Arrays to unmap before running

    def initialize(self, device=None, **kwargs):
        super().initialize(**kwargs)
        if device is None:
            device = Device(backend="auto")
        self.device = device
        force_numpy = bool(root.common.engine.get("force_numpy", False))
        if isinstance(device, NumpyDevice) or force_numpy or not device.exists:
            self._backend_run_ = self.numpy_run
            self.numpy_init()
        else:
            self._backend_run_ = self.tpu_run
            self.tpu_init()

    # -- per-backend hooks ---------------------------------------------------
    def numpy_init(self):
        pass

    def tpu_init(self):
        """Build the jitted kernel.  Default: jit ``self.kernel``."""
        import jax
        if type(self).kernel is not AcceleratedUnit.kernel:
            self._jitted_ = jax.jit(self.kernel)

    def kernel(self, *arrays):  # pragma: no cover - interface doc
        raise NotImplementedError

    def numpy_run(self):
        raise NotImplementedError(
            "%s has no numpy twin" % type(self).__name__)

    def tpu_run(self):
        """Gather declared inputs, run the jitted kernel, store outputs."""
        ins = []
        for name in self.device_inputs:
            arr = getattr(self, name)
            ins.append(arr.devmem if isinstance(arr, Array) else arr)
        outs = self._jitted_(*ins)
        if not isinstance(outs, (tuple, list)):
            outs = (outs,)
        if len(outs) != len(self.device_outputs):
            raise ValueError(
                "%s.kernel returned %d outputs but device_outputs declares "
                "%d" % (type(self).__name__, len(outs),
                        len(self.device_outputs)))
        for name, val in zip(self.device_outputs, outs):
            arr = getattr(self, name)
            if isinstance(arr, Array):
                arr.devmem = val
            else:
                setattr(self, name, val)

    # -- run dispatch --------------------------------------------------------
    def run(self):
        self._backend_run_()
        if bool(root.common.engine.get("sync_run", False)):
            self.device.sync()

    def unmap_vectors(self, *arrays):
        """Push host-dirty Arrays to the device before kernel launch
        (reference accelerated_units.py:448)."""
        for arr in arrays:
            if isinstance(arr, Array):
                arr.unmap()


class DeviceBenchmark(AcceleratedUnit):
    """Square-GEMM timing probe; the "computing power" number used for
    slave load balancing (reference accelerated_units.py:706-824)."""

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.size = kwargs.get("size", 1024)
        self.repeats = kwargs.get("repeats", 4)
        self.result = None

    def tpu_init(self):
        pass

    def tpu_run(self):
        self.result = self.device.benchmark(self.size, repeats=self.repeats)

    def numpy_run(self):
        dev = self.device if isinstance(self.device, NumpyDevice) \
            else NumpyDevice()
        self.result = dev.benchmark(min(self.size, 512))

    def estimate(self):
        if self.result is None:
            self.run()
        return self.result


class AcceleratedWorkflow(object):
    """Mixin for workflows holding a Device (reference
    accelerated_units.py:827-900); the Device travels to member units via
    Workflow.initialize(device=...)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.device = None


def numpy_to_device(x, dtype=None):
    """Convenience device_put with optional dtype cast."""
    import jax
    x = numpy.asarray(x, dtype) if dtype else numpy.asarray(x)
    return jax.device_put(x)
