"""Feature normalizers with a streaming analyze pass.

TPU-native re-design of /root/reference/veles/normalization.py (registry at
:110-124, the eight MAPPING'd families :260-660).  Same behavioral contract:
``analyze(batch)`` accumulates statistics over a streaming pass (the loader
calls it per-minibatch during its normalization analysis,
loader/base.py:760-800), ``normalize(data)`` mutates in place,
``denormalize`` inverts, and normalizer state pickles into snapshots.

The math is plain numpy on purpose: analysis happens once, host-side, at
dataset load; the *per-step* application is fused into the jitted input
pipeline via :meth:`NormalizerBase.jax_apply` which returns the same
transform as a pure jnp expression (the TPU replacement for the reference's
``mean_disp_normalizer`` device kernel, ocl/mean_disp_normalizer.cl).
"""

import numpy

from .registry import MappedObjectsRegistry


class UninitializedStateError(Exception):
    pass


class NormalizerBase(metaclass=MappedObjectsRegistry):
    """Base: streaming analyze + in-place normalize/denormalize."""

    mapping = "normalizer"

    def __init__(self, state=None, **kwargs):
        self._initialized = False
        if state is not None:
            self.state = state

    # -- streaming analysis --------------------------------------------------
    def analyze(self, data):
        data = numpy.asarray(data)
        if not self._initialized:
            self._initialize(data)
            self._initialized = True
        self._analyze(data)

    def analyze_and_normalize(self, data):
        self.analyze(data)
        self.normalize(data)
        return data

    def _initialize(self, data):
        pass

    def _analyze(self, data):
        pass

    # -- application ---------------------------------------------------------
    def normalize(self, data):
        raise NotImplementedError

    def denormalize(self, data):
        raise NotImplementedError

    def jax_apply(self, x):
        """The same transform as a pure jnp expression for fusion into the
        jitted input pipeline.  Default: run numpy path via callback-free
        broadcastable coefficients; stateless subclasses override."""
        raise NotImplementedError(
            "%s cannot be fused; apply host-side" % type(self).__name__)

    # -- snapshot state ------------------------------------------------------
    @property
    def state(self):
        if not self._initialized and self._has_state():
            raise UninitializedStateError(
                "uninitialized normalizers have no state")
        return {k: v for k, v in self.__dict__.items()
                if k != "_initialized"}

    @state.setter
    def state(self, value):
        if not isinstance(value, dict):
            raise TypeError("state must be a dict")
        self.__dict__.update(value)
        self._initialized = True

    def _has_state(self):
        return True

    def __getstate__(self):
        return dict(self.__dict__)

    def __setstate__(self, state):
        self.__dict__.update(state)


class StatelessNormalizer(NormalizerBase):
    """analyze() is a no-op (reference normalization.py:260-282)."""

    def analyze(self, data):
        self._initialized = True

    def _has_state(self):
        return False


class NoneNormalizer(StatelessNormalizer):
    MAPPING = "none"

    def normalize(self, data):
        return data

    def denormalize(self, data):
        return data

    def jax_apply(self, x):
        return x


class MeanDispersionNormalizer(NormalizerBase):
    """(x - mean) / (max - min), computed featurewise over the analyze pass.

    Note: like the reference (normalization.py:284-319), "dispersion" is the
    max-min spread, not the statistical variance.
    """

    MAPPING = "mean_disp"

    def _initialize(self, data):
        self._sum = numpy.zeros_like(data[0], dtype=numpy.float64)
        self._count = 0
        self._min = numpy.array(data[0], dtype=numpy.float64)
        self._max = numpy.array(data[0], dtype=numpy.float64)

    def _analyze(self, data):
        self._count += data.shape[0]
        self._sum += numpy.sum(data, axis=0, dtype=numpy.float64)
        numpy.minimum(self._min, data.min(axis=0), self._min)
        numpy.maximum(self._max, data.max(axis=0), self._max)

    @property
    def coefficients(self):
        mean = self._sum / self._count
        disp = self._max - self._min
        disp = numpy.where(disp == 0, 1.0, disp)
        return mean, disp

    def normalize(self, data):
        mean, disp = self.coefficients
        data -= mean
        data /= disp
        return data

    def denormalize(self, data):
        mean, disp = self.coefficients
        data *= disp
        data += mean
        return data

    def jax_apply(self, x):
        import jax.numpy as jnp
        mean, disp = self.coefficients
        return (x - jnp.asarray(mean, x.dtype)) * jnp.asarray(
            1.0 / disp, x.dtype)


class LinearNormalizer(StatelessNormalizer):
    """Samplewise linear map of each sample's [min, max] onto ``interval``
    (reference normalization.py:347-396)."""

    MAPPING = "linear"

    def __init__(self, state=None, interval=(-1, 1), **kwargs):
        super().__init__(state, **kwargs)
        if state is None:
            self.interval = (float(interval[0]), float(interval[1]))

    def normalize(self, data):
        flat = data.reshape(len(data), -1)
        dmin = flat.min(axis=1, keepdims=True)
        dmax = flat.max(axis=1, keepdims=True)
        imin, imax = self.interval
        diff = dmax - dmin
        uniform = (diff == 0)
        diff = numpy.where(uniform, 1.0, diff)
        # out = (x - dmin) * (imax - imin) / diff + imin;
        # uniform samples land on the interval midpoint (reference
        # normalization.py:363-374)
        flat -= dmin
        flat *= (imax - imin) / diff
        flat += imin
        if uniform.any():
            flat[uniform[:, 0]] = (imin + imax) / 2
        return data

    def jax_apply(self, x):
        import jax.numpy as jnp
        flat = x.reshape(x.shape[0], -1)
        dmin = flat.min(axis=1, keepdims=True)
        dmax = flat.max(axis=1, keepdims=True)
        imin, imax = self.interval
        diff = dmax - dmin
        safe = jnp.where(diff == 0, 1.0, diff)
        out = (flat - dmin) * ((imax - imin) / safe) + imin
        out = jnp.where(diff == 0, (imin + imax) / 2, out)
        return out.reshape(x.shape)


class RangeLinearNormalizer(NormalizerBase):
    """Linear map of the *global* [min, max] (from analyze) onto ``interval``
    (reference normalization.py:398-464)."""

    MAPPING = "range_linear"

    def __init__(self, state=None, interval=(-1, 1), **kwargs):
        super().__init__(state, **kwargs)
        if state is None:
            self.interval = (float(interval[0]), float(interval[1]))

    def _initialize(self, data):
        self._min = float(numpy.min(data))
        self._max = float(numpy.max(data))

    def _analyze(self, data):
        self._min = min(self._min, float(numpy.min(data)))
        self._max = max(self._max, float(numpy.max(data)))

    def normalize(self, data):
        imin, imax = self.interval
        diff = self._max - self._min or 1.0
        data -= self._min
        data *= (imax - imin) / diff
        data += imin
        return data

    def denormalize(self, data):
        imin, imax = self.interval
        diff = self._max - self._min or 1.0
        data -= imin
        data *= diff / (imax - imin)
        data += self._min
        return data

    def jax_apply(self, x):
        imin, imax = self.interval
        diff = self._max - self._min or 1.0
        return (x - self._min) * ((imax - imin) / diff) + imin


class ExponentNormalizer(StatelessNormalizer):
    """Samplewise softmax: exp(x - max) / sum (reference
    normalization.py:467-494)."""

    MAPPING = "exp"

    def normalize(self, data):
        flat = data.reshape(len(data), -1)
        flat -= flat.max(axis=1, keepdims=True)
        numpy.exp(flat, flat)
        flat /= flat.sum(axis=1, keepdims=True)
        return data

    def denormalize(self, data):
        flat = data.reshape(len(data), -1)
        numpy.log(flat, flat)
        return data

    def jax_apply(self, x):
        import jax
        return jax.nn.softmax(x.reshape(x.shape[0], -1)).reshape(x.shape)


class PointwiseNormalizer(NormalizerBase):
    """Featurewise map of the analyzed per-feature [min, max] onto [-1, 1]
    (reference normalization.py:511-563)."""

    MAPPING = "pointwise"

    def _initialize(self, data):
        self._min = numpy.array(data[0], dtype=numpy.float64)
        self._max = numpy.array(data[0], dtype=numpy.float64)

    def _analyze(self, data):
        numpy.minimum(self._min, data.min(axis=0), self._min)
        numpy.maximum(self._max, data.max(axis=0), self._max)

    @property
    def coefficients(self):
        diff = self._max - self._min
        disp = numpy.where(diff == 0, 1.0, diff)
        mul = 2.0 / disp
        add = -1.0 - self._min * mul
        return mul, add

    def normalize(self, data):
        mul, add = self.coefficients
        data *= mul
        data += add
        return data

    def denormalize(self, data):
        mul, add = self.coefficients
        data -= add
        data /= mul
        return data

    def jax_apply(self, x):
        import jax.numpy as jnp
        mul, add = self.coefficients
        return x * jnp.asarray(mul, x.dtype) + jnp.asarray(add, x.dtype)


class ExternalMeanNormalizer(StatelessNormalizer):
    """Subtract a supplied mean array (e.g. an ImageNet mean image;
    reference normalization.py:593-633)."""

    MAPPING = "external_mean"

    def __init__(self, state=None, mean_source=None, scale=1.0, **kwargs):
        super().__init__(state, **kwargs)
        if state is None:
            if mean_source is None:
                raise ValueError("external_mean requires mean_source")
            if isinstance(mean_source, str):
                mean_source = numpy.load(mean_source)
            self.mean = numpy.asarray(mean_source)
            self.scale = float(scale)

    def normalize(self, data):
        data -= self.mean
        if self.scale != 1.0:
            data *= self.scale
        return data

    def denormalize(self, data):
        if self.scale != 1.0:
            data /= self.scale
        data += self.mean
        return data

    def jax_apply(self, x):
        import jax.numpy as jnp
        return (x - jnp.asarray(self.mean, x.dtype)) * x.dtype.type(
            self.scale)


class InternalMeanNormalizer(NormalizerBase):
    """Subtract the mean computed over the analyze pass (reference
    normalization.py:636-660)."""

    MAPPING = "internal_mean"

    def __init__(self, state=None, scale=1.0, **kwargs):
        super().__init__(state, **kwargs)
        if state is None:
            self.scale = float(scale)

    def _initialize(self, data):
        self._sum = numpy.zeros_like(data[0], dtype=numpy.float64)
        self._count = 0

    def _analyze(self, data):
        self._sum += numpy.sum(data, axis=0, dtype=numpy.float64)
        self._count += data.shape[0]

    @property
    def mean(self):
        return self._sum / self._count

    def normalize(self, data):
        data -= self.mean
        if self.scale != 1.0:
            data *= self.scale
        return data

    def denormalize(self, data):
        if self.scale != 1.0:
            data /= self.scale
        data += self.mean
        return data

    def jax_apply(self, x):
        import jax.numpy as jnp
        return (x - jnp.asarray(self.mean, x.dtype)) * x.dtype.type(
            self.scale)


def factory(name, **kwargs):
    """Instantiate a normalizer by MAPPING key."""
    return MappedObjectsRegistry.get("normalizer", name)(**kwargs)
