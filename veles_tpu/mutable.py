"""Mutable boolean gate expressions and cross-unit attribute links.

Re-design of the reference's gate algebra (/root/reference/veles/mutable.py:
``Bool`` at :44, ``LinkableAttribute`` at :219).  A :class:`Bool` is a mutable
truth cell; combining Bools with ``&``, ``|``, ``^`` and ``~`` produces *lazy*
expression Bools that re-evaluate their operands every time they are tested,
so a unit gate such as ``decision.complete | loader.epoch_ended`` tracks its
inputs live.  Assignment is ``b <<= value``.
"""


class Bool:
    """Mutable boolean with lazy operator expressions.

    >>> a, b = Bool(False), Bool(True)
    >>> expr = a | b
    >>> bool(expr)
    True
    >>> b <<= False
    >>> bool(expr)
    False
    """

    __slots__ = ("_value", "_expr", "on_true", "on_false", "name")

    def __init__(self, value=False, name=None):
        self._expr = None
        self._value = bool(value)
        self.on_true = None
        self.on_false = None
        self.name = name

    # -- evaluation ----------------------------------------------------------
    def __bool__(self):
        if self._expr is not None:
            return self._expr()
        return self._value

    def __ilshift__(self, value):
        """``b <<= x`` assigns; fires on_true/on_false callbacks on edges."""
        if self._expr is not None:
            raise ValueError("cannot assign to a derived Bool expression")
        old = self._value
        self._value = bool(value)
        if self._value and not old and self.on_true is not None:
            self.on_true()
        if not self._value and old and self.on_false is not None:
            self.on_false()
        return self

    # -- operators (lazy) ----------------------------------------------------
    @staticmethod
    def _coerce(other):
        if isinstance(other, Bool):
            return other
        return Bool(bool(other))

    def _derived(self, fn, name):
        b = Bool(name=name)
        b._expr = fn
        return b

    def __or__(self, other):
        other = Bool._coerce(other)
        return self._derived(lambda: bool(self) or bool(other),
                             "(%s | %s)" % (self, other))

    __ror__ = __or__

    def __and__(self, other):
        other = Bool._coerce(other)
        return self._derived(lambda: bool(self) and bool(other),
                             "(%s & %s)" % (self, other))

    __rand__ = __and__

    def __xor__(self, other):
        other = Bool._coerce(other)
        return self._derived(lambda: bool(self) != bool(other),
                             "(%s ^ %s)" % (self, other))

    __rxor__ = __xor__

    def __invert__(self):
        return self._derived(lambda: not bool(self), "~%s" % self)

    @classmethod
    def from_callable(cls, fn, name=None):
        """A derived Bool evaluating ``fn()`` each test — for gates over
        non-Bool state (e.g. ``loader.minibatch_class != TRAIN``)."""
        b = cls(name=name)
        b._expr = lambda: bool(fn())
        return b

    # -- misc ----------------------------------------------------------------
    @property
    def is_derived(self):
        return self._expr is not None

    def __repr__(self):
        if self.name:
            return self.name
        if self._expr is not None:
            return "<Bool expr=%s>" % bool(self)
        return "<Bool %s>" % self._value

    def __getstate__(self):
        # Derived expressions cannot be pickled (they close over operands in
        # the live graph); they are reconstructed by re-linking on restore.
        return {"value": bool(self), "name": self.name}

    def __setstate__(self, state):
        self._expr = None
        self._value = state["value"]
        self.name = state.get("name")
        self.on_true = self.on_false = None


def link_attribute(dst, name, src, src_name, two_way=False):
    """Make ``dst.name`` a live pointer to ``src.src_name``.

    Serves the role of the reference LinkableAttribute (veles/mutable.py:219)
    but the routing lives in ``dst.__dict__['_linked_attrs']`` and is honored
    by ``Unit.__getattribute__``/``__setattr__`` — no class mutation, so
    instances of one class may link differently.  ``two_way=True`` propagates
    writes back to the source; one-way writes break the link (reference
    semantics: the attribute becomes locally owned again).
    """
    dst.__dict__.setdefault("_linked_attrs", {})[name] = (src, src_name,
                                                          bool(two_way))


def unlink_attribute(dst, name):
    dst.__dict__.get("_linked_attrs", {}).pop(name, None)
