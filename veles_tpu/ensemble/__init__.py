"""Ensemble training/testing (reference veles/ensemble/).

The reference's ``--ensemble-train size:ratio`` trained N model instances
as subprocesses of ``veles.__main__``, each on a random train subset,
collecting one results JSON per instance
(/root/reference/veles/ensemble/base_workflow.py:59-141, model_workflow.py
:50-137); test mode aggregated the instances' outputs.

TPU-native equivalent: each instance is a subprocess of our CLI with a
distinct ``--random-seed`` (so the loader's shuffle — and therefore the
``train_ratio`` subset — differs per instance) and
``root.common.ensemble.train_ratio`` applied by the Loader base.  Train
results (including each instance's best snapshot path when a snapshotter
runs) land in one ensemble JSON; :func:`test` restores every instance's
snapshot and averages the softmax outputs over the validation set —
probability-averaging ensemble inference on device.
"""

import json
import os
import sys

import numpy


def train(model, size, train_ratio=1.0, argv=(), out_file=None,
          base_seed=1000, python=None, timeout=None, silent=False,
          env=None, scheduler=None):
    """Train ``size`` instances, return the aggregated results dict.

    With ``scheduler`` (a :class:`veles_tpu.jobserver.JobMaster`), the
    instances run concurrently on whatever workers are connected — the
    reference farmed ensemble instances to its slaves the same way
    (ensemble/base_workflow.py:134-141)."""
    python = python or sys.executable
    # an explicit train-ratio override already in the trial argv (e.g.
    # from the --train-ratio flag) wins over our default
    ratio_override = ["root.common.ensemble.train_ratio=%r" % train_ratio]
    if any(str(a).startswith("root.common.ensemble.train_ratio=")
           for a in argv):
        ratio_override = []
    trial_argvs = [list(argv) + ratio_override +
                   ["--random-seed", str(base_seed + i)]
                   for i in range(size)]
    if scheduler is not None:
        outcomes = scheduler.map(
            [{"kind": "trial", "model": model, "argv": ta,
              "timeout": timeout, "env": dict(env) if env else None}
             for ta in trial_argvs])
    else:
        from ..subproc import run_trial
        outcomes = []
        for ta in trial_argvs:
            rc, results, error = run_trial(model, ta, timeout=timeout,
                                           env=env, python=python)
            outcomes.append({"rc": rc, "results": results, "error": error,
                             "worker": None})
    instances = []
    for i, out in enumerate(outcomes):
        entry = {"instance": i, "seed": base_seed + i, "rc": out["rc"]}
        if out.get("worker") is not None:
            entry["worker"] = out["worker"]
        if out.get("results") is not None:
            entry["results"] = out["results"]
        else:
            entry["error"] = out.get("error")
        instances.append(entry)
        if not silent:
            print("ensemble instance %d/%d%s: rc=%s %s" % (
                i + 1, size,
                " (worker %s)" % out["worker"] if out.get("worker")
                else "", out["rc"],
                entry.get("results", entry.get("error", ""))))
    summary = aggregate(instances)
    out = {"model": model, "size": size, "train_ratio": train_ratio,
           "instances": instances, "summary": summary}
    if out_file:
        with open(out_file, "w") as f:
            json.dump(out, f, indent=2)
    return out


def aggregate(instances):
    """Summarize per-instance metrics: mean/std/best of every numeric."""
    keys = {}
    for entry in instances:
        for k, v in entry.get("results", {}).items():
            if isinstance(v, (int, float)) and v is not None:
                keys.setdefault(k, []).append(float(v))
    return {k: {"mean": float(numpy.mean(v)), "std": float(numpy.std(v)),
                "min": float(numpy.min(v)), "max": float(numpy.max(v)),
                "n": len(v)}
            for k, v in keys.items()}


def test(ensemble_file_or_dict, device=None):
    """Averaged-probability ensemble inference over the validation set.

    Restores every instance's best snapshot (``Snapshot`` result key),
    runs the forward chain on its loader's validation samples, averages
    the class probabilities across instances, and reports the voted
    error rate (reference ensemble/test_workflow.py role)."""
    import jax
    import jax.numpy as jnp
    from ..loader.base import VALID
    from ..snapshotter import restore
    from ..backends import Device

    if isinstance(ensemble_file_or_dict, str):
        with open(ensemble_file_or_dict) as f:
            ensemble = json.load(f)
    else:
        ensemble = ensemble_file_or_dict
    device = device or Device(backend="auto")
    probs_sum = None
    labels = None
    used = 0
    for entry in ensemble["instances"]:
        snap = entry.get("results", {}).get("Snapshot")
        if not snap or not os.path.exists(snap):
            continue
        wf = restore(snap)
        wf.initialize(device=device)
        ld = wf.loader
        start = ld.class_end_offsets[VALID] - ld.class_lengths[VALID]
        end = ld.class_end_offsets[VALID]
        data = numpy.asarray(ld.original_data.map_read()[start:end])
        data = data.reshape(len(data), -1) if data.ndim == 2 or \
            wf.forwards[0].MAPPING.startswith("all2all") else data
        if labels is None:
            labels = numpy.asarray(ld._dense_labels[start:end])
        params = [f.params for f in wf.forwards]

        def forward(params, x, forwards=wf.forwards):
            h = x
            for i, f in enumerate(forwards):
                h = f.apply(params[i], h)
            return h
        out = jax.jit(forward)(params, jnp.asarray(data))
        p = jax.nn.softmax(out) if out.shape[-1] > 1 else out
        probs_sum = p if probs_sum is None else probs_sum + p
        used += 1
    if not used:
        raise ValueError("no instance has a restorable Snapshot result")
    pred = numpy.asarray(jnp.argmax(probs_sum, axis=-1))
    err_pt = 100.0 * float((pred != labels).mean())
    return {"instances_used": used, "validation_error_pt": err_pt,
            "n_valid": int(len(labels))}
