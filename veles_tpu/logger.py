"""Logger subsystem: class-level loggers + JSONL event tracing.

TPU-native re-creation of /root/reference/veles/logger.py: the reference
gave every class a colored console logger (:1-200) and an
``event(name, "begin"|"end"|"single", **info)`` stream duplicated into
MongoDB (:264-289).  Here the event stream is a **Chrome-trace JSONL
file** (one event object per line, ``ph`` B/E/X/i phases) — loadable in
Perfetto/chrome://tracing next to jax-profiler traces, greppable, and
zero-dependency — instead of a Mongo collection.

Enable via config::

    root.common.trace.enabled = True
    root.common.trace.file = "events.jsonl"      # default: events dir

or ``Unit.execute`` emits per-run spans automatically when enabled.
"""

import atexit
import json
import logging
import os
import sys
import threading
import time

from .config import root
from .observability import trace as _trace

_COLORS = {"DEBUG": "\033[37m", "INFO": "\033[32m", "WARNING": "\033[33m",
           "ERROR": "\033[31m", "CRITICAL": "\033[41m"}
_RESET = "\033[0m"


class ColorFormatter(logging.Formatter):
    """Reference-style colored console lines (logger.py:60-120)."""

    def format(self, record):
        text = super().format(record)
        if sys.stderr.isatty():
            color = _COLORS.get(record.levelname, "")
            return "%s%s%s" % (color, text, _RESET) if color else text
        return text


def setup_logging(level=logging.INFO, file=None):
    """Install the colored console handler (+ optional duplicate-to-file,
    reference Logger.redirect_all_logging_to_file)."""
    rt = logging.getLogger()
    rt.setLevel(level)
    rt.handlers = [h for h in rt.handlers
                   if not getattr(h, "_veles_tpu", False)]
    console = logging.StreamHandler()
    console.setFormatter(ColorFormatter(
        "%(asctime)s %(levelname)s %(name)s: %(message)s", "%H:%M:%S"))
    console._veles_tpu = True
    rt.addHandler(console)
    if file:
        fh = logging.FileHandler(file)
        fh.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s"))
        fh._veles_tpu = True
        rt.addHandler(fh)


class Logger:
    """Mixin giving every class its own named logger (reference
    veles/logger.py Logger mixin)."""

    @property
    def logger(self):
        return logging.getLogger(type(self).__name__)

    def debug(self, msg, *args):
        self.logger.debug(msg, *args)

    def info(self, msg, *args):
        self.logger.info(msg, *args)

    def warning(self, msg, *args):
        self.logger.warning(msg, *args)

    def error(self, msg, *args):
        self.logger.error(msg, *args)


class EventLog:
    """Chrome-trace JSONL writer (the Mongo events replacement).

    Phases: ``begin``/``end`` spans, ``single`` instants, and ``span``
    complete events with explicit duration — mapping to trace-viewer
    ``B``/``E``/``i``/``X``."""

    _PH = {"begin": "B", "end": "E", "single": "i", "span": "X"}

    def __init__(self, path=None):
        self._path = path
        self._file = None
        self._lock = threading.Lock()
        self.path = None
        #: optional in-process mirror (the flight recorder's span
        #: bridge): called as ``sink(name, kind, duration, info)``
        #: BEFORE the enabled gate, so per-request timelines work even
        #: when file tracing is off.  Exceptions are swallowed —
        #: observability never takes down the caller.
        self.span_sink = None
        # perf_counter, not time.time(): a wall-clock jump (NTP step,
        # suspend/resume) must never produce out-of-order or
        # negative-duration trace events
        self._t0 = time.perf_counter()

    @property
    def enabled(self):
        # VELES_TRACE_DIR enables tracing in ANY veles_tpu process —
        # the zero-plumbing switch that makes spawned workers trace
        # (jobserver.WorkerPool children inherit the environment)
        return bool(root.common.trace.get("enabled", False) or
                    os.environ.get("VELES_TRACE_DIR"))

    def _ensure_open(self):
        if self._file is not None:
            return
        trace_dir = os.environ.get("VELES_TRACE_DIR")
        path = (self._path or root.common.trace.get("file") or
                (os.path.join(trace_dir, "events-%d.jsonl" % os.getpid())
                 if trace_dir else None) or
                os.path.join(root.common.dirs.get("events", "."),
                             "events-%d.jsonl" % os.getpid()))
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._file = open(path, "a", buffering=1)  # line buffered
        self.path = path
        # wall-clock anchor: ts values are per-process perf_counter
        # deltas; this record lets tools/merge_traces.py align several
        # processes' files onto one absolute timeline
        self._file.write(json.dumps({
            "name": "trace_start", "ph": "i",
            "ts": round((time.perf_counter() - self._t0) * 1e6, 1),
            "pid": os.getpid(), "tid": threading.get_ident(),
            "args": {"unix_time_s": time.time()}}) + "\n")
        atexit.register(self.close)

    def event(self, name, kind="single", duration=None, **info):
        """Record one event; no-op unless tracing is enabled (the
        ``span_sink`` mirror fires regardless — it is memory-only)."""
        sink = self.span_sink
        if sink is not None:
            try:
                sink(name, kind, duration, info)
            except Exception:  # noqa: BLE001 — diagnostics never raise
                pass
        if not self.enabled:
            return
        ctx = _trace.current()
        with self._lock:
            self._ensure_open()
            ts = time.perf_counter() - self._t0
            if duration is not None:
                ts -= duration  # trace-viewer X events anchor at start
            record = {"name": name, "ph": self._PH.get(kind, "i"),
                      "ts": round(ts * 1e6, 1),
                      "pid": os.getpid(), "tid": threading.get_ident()}
            if duration is not None:
                record["dur"] = round(duration * 1e6, 1)
            if ctx is not None:
                # causal links ride in args (trace viewers show them;
                # explicit trace_id/span kwargs win via setdefault)
                info = dict(info) if info else {}
                info.setdefault("trace_id", ctx.trace_id)
                info.setdefault("span", ctx.span_id)
                if ctx.parent_id:
                    info.setdefault("parent_span", ctx.parent_id)
            if info:
                record["args"] = info
            self._file.write(json.dumps(record) + "\n")

    def span(self, name, seconds, **info):
        """Complete span ending now, lasting ``seconds``."""
        self.event(name, "span", duration=seconds, **info)

    def close(self):
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None

    def reset(self):
        """Close the output and forget every path decision so the next
        event re-resolves its destination from config/env — THE way for
        tests (and forked workers) to return the process-global log to
        its pristine state instead of poking ``_path``/``_file``."""
        self.close()
        with self._lock:
            self._path = None
            self.path = None
            self._t0 = time.perf_counter()


#: process-global event log (reference: per-node Mongo duplication)
events = EventLog()
