"""Logger subsystem: class-level loggers + JSONL event tracing.

TPU-native re-creation of /root/reference/veles/logger.py: the reference
gave every class a colored console logger (:1-200) and an
``event(name, "begin"|"end"|"single", **info)`` stream duplicated into
MongoDB (:264-289).  Here the event stream is a **Chrome-trace JSONL
file** (one event object per line, ``ph`` B/E/X/i phases) — loadable in
Perfetto/chrome://tracing next to jax-profiler traces, greppable, and
zero-dependency — instead of a Mongo collection.

Enable via config::

    root.common.trace.enabled = True
    root.common.trace.file = "events.jsonl"      # default: events dir

or ``Unit.execute`` emits per-run spans automatically when enabled.
"""

import atexit
import json
import logging
import os
import sys
import threading
import time

from .config import root

_COLORS = {"DEBUG": "\033[37m", "INFO": "\033[32m", "WARNING": "\033[33m",
           "ERROR": "\033[31m", "CRITICAL": "\033[41m"}
_RESET = "\033[0m"


class ColorFormatter(logging.Formatter):
    """Reference-style colored console lines (logger.py:60-120)."""

    def format(self, record):
        text = super().format(record)
        if sys.stderr.isatty():
            color = _COLORS.get(record.levelname, "")
            return "%s%s%s" % (color, text, _RESET) if color else text
        return text


def setup_logging(level=logging.INFO, file=None):
    """Install the colored console handler (+ optional duplicate-to-file,
    reference Logger.redirect_all_logging_to_file)."""
    rt = logging.getLogger()
    rt.setLevel(level)
    rt.handlers = [h for h in rt.handlers
                   if not getattr(h, "_veles_tpu", False)]
    console = logging.StreamHandler()
    console.setFormatter(ColorFormatter(
        "%(asctime)s %(levelname)s %(name)s: %(message)s", "%H:%M:%S"))
    console._veles_tpu = True
    rt.addHandler(console)
    if file:
        fh = logging.FileHandler(file)
        fh.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s"))
        fh._veles_tpu = True
        rt.addHandler(fh)


class Logger:
    """Mixin giving every class its own named logger (reference
    veles/logger.py Logger mixin)."""

    @property
    def logger(self):
        return logging.getLogger(type(self).__name__)

    def debug(self, msg, *args):
        self.logger.debug(msg, *args)

    def info(self, msg, *args):
        self.logger.info(msg, *args)

    def warning(self, msg, *args):
        self.logger.warning(msg, *args)

    def error(self, msg, *args):
        self.logger.error(msg, *args)


class EventLog:
    """Chrome-trace JSONL writer (the Mongo events replacement).

    Phases: ``begin``/``end`` spans, ``single`` instants, and ``span``
    complete events with explicit duration — mapping to trace-viewer
    ``B``/``E``/``i``/``X``."""

    _PH = {"begin": "B", "end": "E", "single": "i", "span": "X"}

    def __init__(self, path=None):
        self._path = path
        self._file = None
        self._lock = threading.Lock()
        self._t0 = time.time()

    @property
    def enabled(self):
        return bool(root.common.trace.get("enabled", False))

    def _ensure_open(self):
        if self._file is not None:
            return
        path = (self._path or root.common.trace.get("file") or
                os.path.join(root.common.dirs.get("events", "."),
                             "events-%d.jsonl" % os.getpid()))
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._file = open(path, "a", buffering=1)  # line buffered
        self.path = path
        atexit.register(self.close)

    def event(self, name, kind="single", duration=None, **info):
        """Record one event; no-op unless tracing is enabled."""
        if not self.enabled:
            return
        with self._lock:
            self._ensure_open()
            ts = time.time() - self._t0
            if duration is not None:
                ts -= duration  # trace-viewer X events anchor at start
            record = {"name": name, "ph": self._PH.get(kind, "i"),
                      "ts": round(ts * 1e6, 1),
                      "pid": os.getpid(), "tid": threading.get_ident()}
            if duration is not None:
                record["dur"] = round(duration * 1e6, 1)
            if info:
                record["args"] = info
            self._file.write(json.dumps(record) + "\n")

    def span(self, name, seconds, **info):
        """Complete span ending now, lasting ``seconds``."""
        self.event(name, "span", duration=seconds, **info)

    def close(self):
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None


#: process-global event log (reference: per-node Mongo duplication)
events = EventLog()
