"""Class registries.

Re-design of the reference registries:
- ``UnitRegistry`` metaclass auto-registers every Unit subclass for
  introspection and the frontend (reference: veles/unit_registry.py:51).
- ``MappedObjectsRegistry`` maps string keys to classes for pluggable families
  (normalizers, snapshotters, loaders; reference:
  veles/mapped_object_registry.py).
"""

import uuid


class UnitRegistry(type):
    """Metaclass: every concrete Unit subclass lands in ``UnitRegistry.units``.

    Classes may set ``hide_from_registry = True`` (abstract bases) and may
    carry a stable ``UUID`` used by the export path (the reference's C++
    UnitFactory resolves units by UUID, libVeles/src/unit_factory.cc:37-65).
    """

    units = {}

    def __new__(mcs, name, bases, clsdict):
        cls = super().__new__(mcs, name, bases, clsdict)
        if not clsdict.get("hide_from_registry", False):
            UnitRegistry.units[name] = cls
            if "UUID" not in clsdict:
                # deterministic UUID from qualified name
                cls.UUID = str(uuid.uuid5(uuid.NAMESPACE_DNS,
                                          "veles_tpu." + name))
        return cls

    @staticmethod
    def find(name):
        return UnitRegistry.units.get(name)

    @staticmethod
    def find_by_uuid(uid):
        for cls in UnitRegistry.units.values():
            if getattr(cls, "UUID", None) == uid:
                return cls
        return None


class MappedObjectsRegistry(type):
    """Metaclass for string-keyed class families.

    A family base sets ``mapping = "familyname"`` and a fresh ``registry``
    dict; members set ``MAPPING = "key"``.
    """

    registries = {}

    def __new__(mcs, name, bases, clsdict):
        cls = super().__new__(mcs, name, bases, clsdict)
        family = getattr(cls, "mapping", None)
        if family is not None:
            reg = MappedObjectsRegistry.registries.setdefault(family, {})
            key = clsdict.get("MAPPING")
            if key is not None:
                reg[key] = cls
        return cls

    @staticmethod
    def get(family, key):
        try:
            return MappedObjectsRegistry.registries[family][key]
        except KeyError:
            raise KeyError(
                "no %r registered in family %r (have: %s)" % (
                    key, family, sorted(
                        MappedObjectsRegistry.registries.get(family, {}))))
