"""Downloader: fetch + unpack a dataset at workflow initialize.

Re-creation of /root/reference/veles/downloader.py (:56,125): the unit
downloads ``url`` into the datasets directory and unpacks tar/zip
archives before the loader touches ``directory``.  Local ``file://``
URLs and plain paths are first-class (this build runs in zero-egress
environments; HTTP still works where the network allows it).
"""

import os
import shutil
import tarfile
import urllib.parse
import urllib.request
import zipfile

from .config import root
from .units import Unit


class Downloader(Unit):
    MAPPING = "downloader"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.view_group = "SERVICE"
        self.url = kwargs.get("url")
        self.directory = kwargs.get("directory") or \
            root.common.dirs.get("datasets", ".")
        # files whose presence means the dataset is already there
        self.files = list(kwargs.get("files", ()))

    @property
    def ready(self):
        return self.files and all(
            os.path.exists(os.path.join(self.directory, f))
            for f in self.files)

    def initialize(self, **kwargs):
        super().initialize(**kwargs)
        if self.ready:
            return
        if not self.url:
            raise ValueError("dataset files missing and no url given")
        self.fetch()

    def fetch(self):
        os.makedirs(self.directory, exist_ok=True)
        parsed = urllib.parse.urlparse(str(self.url))
        name = os.path.basename(parsed.path) or "download"
        target = os.path.join(self.directory, name)
        if parsed.scheme in ("", "file"):
            src = parsed.path if parsed.scheme == "file" else self.url
            shutil.copy(src, target)
        else:
            urllib.request.urlretrieve(self.url, target)
        self.unpack(target)
        if self.files and not self.ready:
            missing = [f for f in self.files if not os.path.exists(
                os.path.join(self.directory, f))]
            raise FileNotFoundError(
                "downloaded %s but expected files are still missing: %s "
                "(bad archive format or wrong contents?)"
                % (self.url, ", ".join(missing)))
        return target

    def unpack(self, path):
        if tarfile.is_tarfile(path):
            with tarfile.open(path) as tf:
                tf.extractall(self.directory, filter="data")
        elif zipfile.is_zipfile(path):
            with zipfile.ZipFile(path) as zf:
                zf.extractall(self.directory)

    def run(self):
        pass  # all the work happens at initialize, like the reference
