"""Shared CLI-trial runner for the meta-schedulers (GA, ensembles).

Both the genetic optimizer and the ensemble trainer evaluate a model by
re-invoking ``python -m veles_tpu`` as a subprocess with a temp result
file — the same pattern the reference used for its meta-workflows
(optimization_workflow.py:286-296, ensemble/base_workflow.py:134-141).
"""

import json
import os
import subprocess
import sys
import tempfile

from .observability import trace as _trace

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_trial(model, argv, timeout=None, env=None, python=None):
    """Run one CLI trial; returns (rc, results_dict_or_None, error_text).

    ``rc`` is the subprocess exit code (-1 for timeout); ``results`` is
    the parsed ``--result-file`` JSON when the trial succeeded.  When a
    trace context is active (a traced GA/ensemble run, or a jobserver
    worker executing a traced master's job) it is handed to the child
    via the environment, so the trial's own event file joins the same
    distributed trace."""
    python = python or sys.executable
    env = _trace.inject_env(env)
    fd, result_file = tempfile.mkstemp(prefix="veles-tpu-trial-",
                                       suffix=".json")
    os.close(fd)
    try:
        cmd = ([python, "-m", "veles_tpu", model] + list(argv) +
               ["--result-file", result_file])
        try:
            proc = subprocess.run(cmd, timeout=timeout,
                                  capture_output=True, cwd=REPO_ROOT,
                                  env=env)
        except subprocess.TimeoutExpired:
            return -1, None, "timeout after %ss" % timeout
        if proc.returncode:
            return (proc.returncode, None,
                    "exit %d: %s" % (proc.returncode,
                                     proc.stderr.decode()[-2000:]))
        try:
            with open(result_file) as f:
                return 0, json.load(f), None
        except (ValueError, json.JSONDecodeError) as e:
            return 0, None, "bad result JSON: %r" % e
    finally:
        os.unlink(result_file)
