"""Control-flow plumbing units.

Reference: /root/reference/veles/plumbing.py:36-112.
"""

from .units import Unit, TrivialUnit


class StartPoint(TrivialUnit):
    """Workflow entry point; fired by Workflow.run."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("name", "Start")
        super().__init__(workflow, **kwargs)


class EndPoint(TrivialUnit):
    """Workflow exit: running it finishes the workflow (plumbing.py:80)."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("name", "End")
        super().__init__(workflow, **kwargs)

    def run(self):
        self.workflow.on_workflow_finished()


class Repeater(TrivialUnit):
    """Loop head: opens on any input link (ignores the AND-gate)."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("name", "Repeater")
        super().__init__(workflow, **kwargs)
        self.ignores_gate = True


class FireStarter(Unit):
    """Resets the ``stopped`` flag of chosen units so loops may restart
    (plumbing.py:91)."""

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.units_to_fire = list(kwargs.get("units", ()))

    def run(self):
        for unit in self.units_to_fire:
            unit.stopped = False
