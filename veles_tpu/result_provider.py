"""Result provider protocol (reference: veles/result_provider.py).

Units that produce final metrics implement ``get_metric_names`` /
``get_metric_values``; Workflow.gather_results collects them into the
``--result-file`` JSON (reference workflow.py:827-849).
"""


class IResultProvider:
    def get_metric_names(self):
        return set()

    def get_metric_values(self):
        return {}
