"""Dataset acquisition helpers.

The reference ships a ``Downloader`` unit that fetches datasets at workflow
init (/root/reference/veles/downloader.py:56) and the Znicz samples load
MNIST/CIFAR from disk.  This build environment has zero egress, so:

- ``load_mnist()`` reads the standard IDX files when present under
  ``root.common.dirs.datasets`` (same on-disk format the reference
  consumes);
- otherwise it falls back to :func:`synthetic_mnist` — a deterministic
  MNIST-shaped classification problem (10 smooth class templates + noise +
  elastic jitter) with the exact array shapes/dtypes of the real thing, so
  every downstream component (loaders, nets, bench) exercises identically.
"""

import gzip
import os
import struct

import numpy

from .config import root


def _dataset_dir():
    return os.path.expanduser(
        root.common.dirs.get("datasets", "~/.veles_tpu/datasets"))


def _read_idx(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dtype = {0x08: numpy.uint8, 0x09: numpy.int8, 0x0B: numpy.int16,
                 0x0C: numpy.int32, 0x0D: numpy.float32,
                 0x0E: numpy.float64}[(magic >> 8) & 0xFF]
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = numpy.frombuffer(f.read(), numpy.dtype(dtype).newbyteorder(">"))
        return data.reshape(shape).astype(dtype)


def synthetic_mnist(n_train=6000, n_valid=1000, seed=1312, size=28):
    """Deterministic MNIST-shaped 10-class problem.

    Each class is a smooth random template (low-frequency gaussian field);
    samples are the template under small shift + pixel noise.  Linearly
    non-trivial, conv-friendly, and fully reproducible.
    """
    rng = numpy.random.RandomState(seed)
    templates = []
    for _ in range(10):
        coarse = rng.uniform(0, 1, (7, 7))
        fine = numpy.kron(coarse, numpy.ones((4, 4)))[:size, :size]
        # cheap smoothing: two box-blur passes
        for _ in range(2):
            fine = (fine + numpy.roll(fine, 1, 0) + numpy.roll(fine, -1, 0) +
                    numpy.roll(fine, 1, 1) + numpy.roll(fine, -1, 1)) / 5
        templates.append(fine)
    templates = numpy.stack(templates)

    def make(n, rs):
        labels = rs.randint(0, 10, n)
        imgs = templates[labels]
        dx = rs.randint(-2, 3, n)
        dy = rs.randint(-2, 3, n)
        out = numpy.empty_like(imgs)
        for i in range(n):
            out[i] = numpy.roll(numpy.roll(imgs[i], dx[i], 0), dy[i], 1)
        out += rs.normal(0, 0.35, out.shape)
        out = numpy.clip(out, 0, 1.5) / 1.5 * 255
        return out.astype(numpy.uint8), labels.astype(numpy.int32)

    train = make(n_train, numpy.random.RandomState(seed + 1))
    valid = make(n_valid, numpy.random.RandomState(seed + 2))
    return train, valid


def load_mnist(n_train=None, n_valid=None):
    """(train_images, train_labels), (valid_images, valid_labels) as uint8
    arrays; real MNIST when the IDX files exist, synthetic otherwise.
    Returns (train, valid, is_real)."""
    train, valid, provenance = load_digits_idx(n_train, n_valid,
                                               fixture=False)
    return train, valid, provenance == "real"


def fixture_dir():
    """The committed IDX digits fixture, shipped INSIDE the package
    (``veles_tpu/fixtures/digits``) so installed copies and pruned
    checkouts keep the real-file tier; override with
    $VELES_TPU_FIXTURES."""
    env = os.environ.get("VELES_TPU_FIXTURES")
    return env or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "fixtures",
        "digits")


_IDX_NAMES = ["train-images-idx3-ubyte", "train-labels-idx1-ubyte",
              "t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"]


def _find_idx(d):
    paths = []
    for n in _IDX_NAMES:
        for cand in (os.path.join(d, n), os.path.join(d, n + ".gz")):
            if os.path.exists(cand):
                paths.append(cand)
                break
    return paths if len(paths) == 4 else None


def load_digits_idx(n_train=None, n_valid=None, fixture=True):
    """The three-tier digits source, in provenance order:

    1. ``"real"`` — true MNIST IDX files under
       ``root.common.dirs.datasets/mnist`` (drop them there on any host
       with egress; format per http://yann.lecun.com/exdb/mnist/);
    2. ``"fixture"`` — the committed font-rendered IDX archives under
       ``veles_tpu/fixtures/digits`` (tools/make_digits_fixture.py): REAL
       fixed files exercising the identical gz-IDX parse + loader path,
       vendored because this build environment has zero egress;
    3. ``"synthetic"`` — :func:`synthetic_mnist`, generated in-process.

    Returns ((train_images, train_labels), (valid_images, valid_labels),
    provenance_str).  ``fixture=False`` skips tier 2 (used by
    :func:`load_mnist`, whose contract is real-or-synthetic)."""
    tiers = [(os.path.join(_dataset_dir(), "mnist"), "real")]
    if fixture:
        tiers.append((fixture_dir(), "fixture"))
    for d, provenance in tiers:
        paths = _find_idx(d)
        if paths:
            ti, tl, vi, vl = (_read_idx(p) for p in paths)
            return ((ti[:n_train], tl[:n_train].astype(numpy.int32)),
                    (vi[:n_valid], vl[:n_valid].astype(numpy.int32)),
                    provenance)
    train, valid = synthetic_mnist(n_train or 6000, n_valid or 1000)
    return train, valid, "synthetic"
