"""MinibatchesSaver / MinibatchesLoader: materialized minibatch cache.

Re-creation of /root/reference/veles/loader/saver.py: a unit that
records every served minibatch into one pickle stream file, and a Loader
that replays the file — used to freeze an expensive input pipeline
(image decoding, augmentation) into a flat cache.
"""

import pickle

import numpy

from ..units import Unit
from .base import TEST, VALID, TRAIN
from .fullbatch import FullBatchLoader


class MinibatchesSaver(Unit):
    """Streams (class, size, data, labels) records to ``path``."""

    MAPPING = "minibatches_saver"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.path = kwargs.get("path", "minibatches.pickle")
        self.minibatch_data = None      # linked from loader
        self.minibatch_labels = None
        self.minibatch_size = None
        self.minibatch_class = None
        self._file_ = None

    def link_loader(self, loader):
        self.loader = loader
        self.link_attrs(loader, "minibatch_data", "minibatch_labels",
                        "minibatch_size", "minibatch_class")
        return self

    def run(self):
        if self._file_ is None:
            self._file_ = open(self.path, "wb")
        # deferred-gather loaders never fill the host Arrays on their own
        self.loader.materialize_minibatch()
        size = int(self.minibatch_size)
        data = numpy.asarray(self.minibatch_data.map_read()[:size])
        labels = None
        if self.minibatch_labels:
            labels = numpy.asarray(
                self.minibatch_labels.map_read()[:size])
        pickle.dump((int(self.minibatch_class), size, data, labels),
                    self._file_, protocol=pickle.HIGHEST_PROTOCOL)

    def close(self):
        if self._file_ is not None:
            self._file_.close()
            self._file_ = None


class MinibatchesLoader(FullBatchLoader):
    """Replays a MinibatchesSaver file through the Loader protocol.

    The records are concatenated per class into the HBM-resident
    FullBatch dataset, so shuffling/requeueing/device-gather behave
    exactly like any other resident loader."""

    MAPPING = "minibatches_loader"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.path = kwargs.get("path", "minibatches.pickle")

    def load_data(self):
        per_class = {TEST: [], VALID: [], TRAIN: []}
        per_class_labels = {TEST: [], VALID: [], TRAIN: []}
        with open(self.path, "rb") as f:
            while True:
                try:
                    cls, size, data, labels = pickle.load(f)
                except EOFError:
                    break
                per_class[cls].append(data[:size])
                if labels is not None:
                    per_class_labels[cls].extend(labels[:size].tolist())
        chunks, labels = [], []
        for cls in (TEST, VALID, TRAIN):
            n = sum(len(c) for c in per_class[cls])
            self.class_lengths[cls] = n
            if n:
                chunks.append(numpy.concatenate(per_class[cls]))
                labels.extend(per_class_labels[cls])
        if not chunks:
            raise ValueError("no minibatch records in %s" % self.path)
        data = numpy.concatenate(chunks).astype(numpy.float32)
        if labels and len(labels) != len(data):
            # mixed labelled/unlabelled records would silently shift
            # every label onto the wrong sample
            raise ValueError(
                "minibatch cache mixes labelled and unlabelled records "
                "(%d labels for %d samples)" % (len(labels), len(data)))
        self.original_data.mem = data
        self.original_labels = labels
        self.has_labels = bool(labels)
