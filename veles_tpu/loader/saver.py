"""MinibatchesSaver / MinibatchesLoader: materialized minibatch cache.

Re-creation of /root/reference/veles/loader/saver.py: a unit that
records every served minibatch into one pickle stream file, and a Loader
that replays the file — used to freeze an expensive input pipeline
(image decoding, augmentation) into a flat cache.
"""

import pickle

import numpy

from ..units import Unit
from .base import Loader, TEST, VALID, TRAIN


class MinibatchesSaver(Unit):
    """Streams (class, size, data, labels) records to ``path``."""

    MAPPING = "minibatches_saver"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.path = kwargs.get("path", "minibatches.pickle")
        self.minibatch_data = None      # linked from loader
        self.minibatch_labels = None
        self.minibatch_size = None
        self.minibatch_class = None
        self._file_ = None

    def link_loader(self, loader):
        self.link_attrs(loader, "minibatch_data", "minibatch_labels",
                        "minibatch_size", "minibatch_class")
        return self

    def run(self):
        if self._file_ is None:
            self._file_ = open(self.path, "wb")
        size = int(self.minibatch_size)
        data = numpy.asarray(self.minibatch_data.map_read()[:size])
        labels = None
        if self.minibatch_labels:
            labels = numpy.asarray(
                self.minibatch_labels.map_read()[:size])
        pickle.dump((int(self.minibatch_class), size, data, labels),
                    self._file_, protocol=pickle.HIGHEST_PROTOCOL)

    def close(self):
        if self._file_ is not None:
            self._file_.close()
            self._file_ = None


class MinibatchesLoader(Loader):
    """Replays a MinibatchesSaver file through the Loader protocol.

    The records are concatenated per class into a resident dataset, so
    shuffling/requeueing behave exactly like any other loader."""

    MAPPING = "minibatches_loader"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.path = kwargs.get("path", "minibatches.pickle")
        self._data = None
        self._labels = None

    def load_data(self):
        per_class = {TEST: [], VALID: [], TRAIN: []}
        per_class_labels = {TEST: [], VALID: [], TRAIN: []}
        with open(self.path, "rb") as f:
            while True:
                try:
                    cls, size, data, labels = pickle.load(f)
                except EOFError:
                    break
                per_class[cls].append(data[:size])
                if labels is not None:
                    per_class_labels[cls].extend(labels[:size].tolist())
        chunks, labels = [], []
        for cls in (TEST, VALID, TRAIN):
            n = sum(len(c) for c in per_class[cls])
            self.class_lengths[cls] = n
            if n:
                chunks.append(numpy.concatenate(per_class[cls]))
                labels.extend(per_class_labels[cls])
        if not chunks:
            raise ValueError("no minibatch records in %s" % self.path)
        self._data = numpy.concatenate(chunks)
        if labels and len(labels) != len(self._data):
            # mixed labelled/unlabelled records would silently shift
            # every label onto the wrong sample
            raise ValueError(
                "minibatch cache mixes labelled and unlabelled records "
                "(%d labels for %d samples)" % (len(labels),
                                                len(self._data)))
        self._labels = labels
        self.has_labels = bool(labels)

    def create_minibatch_data(self):
        self.minibatch_data.reset(numpy.zeros(
            (self.max_minibatch_size,) + self._data.shape[1:],
            numpy.float32))

    def fill_minibatch(self):
        idx = self.minibatch_indices.map_read()[:self.minibatch_size]
        self.minibatch_data.map_write()[:self.minibatch_size] = \
            self._data[idx]
        if self.has_labels:
            for i, sample_idx in enumerate(idx):
                self.raw_minibatch_labels[i] = self._labels[sample_idx]
