"""Loader: the minibatch-serving unit at the head of every training loop.

TPU-native re-design of /root/reference/veles/loader/base.py (Loader
:100-120; TEST/VALID/TRAIN triage :73-80; master/slave index distribution
:631-663; shuffling :711-724; failed-minibatch requeue :679-687;
normalization analysis pass :760-800).

Epoch model kept intact: the dataset is three classes laid out
``[test | validation | train]``; a global offset walks the concatenated
``shuffled_indices`` and the minibatch class is the segment the offset falls
in.  ``last_minibatch``/``epoch_ended``/``train_ended`` are :class:`Bool`
gates that downstream Decision units link on.  In distributed mode the
master serves *indices only* and slaves gather their own data — the same
contract the mesh data-parallel input pipeline uses per shard.
"""

import collections

import numpy

from ..config import root
from ..memory import Array
from ..mutable import Bool
from ..units import Unit
from ..result_provider import IResultProvider
from .. import prng
from .. import normalization

TARGET = 3
TRAIN = 2
VALID = 1
TEST = 0
TRIAGE = {"train": TRAIN, "validation": VALID, "valid": VALID, "test": TEST}
CLASS_NAME = ["test", "validation", "train"]


class LoaderError(Exception):
    pass


class Loader(Unit, IResultProvider):
    """Serves minibatches from a 3-class dataset.

    Subclasses implement the ILoader trio (reference base.py:100-120):

    - ``load_data()`` — fill ``class_lengths``;
    - ``create_minibatch_data()`` — allocate ``minibatch_data``;
    - ``fill_minibatch()`` — gather ``minibatch_data``/``minibatch_labels``
      for ``minibatch_indices[:minibatch_size]``.

    A subclass may instead override ``fill_indices`` to return True, meaning
    the gather happens on-device (FullBatchLoader's jnp.take path).
    """

    LABEL_DTYPE = numpy.int32
    INDEX_DTYPE = numpy.int32

    hide_from_registry = True
    #: standalone ``run()`` may be wrapped by a background
    #: :class:`~veles_tpu.loader.prefetch.MinibatchPrefetcher`; loaders
    #: whose run() has side channels beyond minibatch serving (stream/
    #: interactive feeds that can stop the workflow) opt out
    supports_prefetch = True

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.view_group = "LOADER"
        self.max_minibatch_size = kwargs.get("minibatch_size", 100)
        self.class_lengths = [0, 0, 0]
        self.class_end_offsets = [0, 0, 0]
        self.minibatch_data = Array()
        self.minibatch_labels = Array()
        self.minibatch_indices = Array()
        self.minibatch_size = 0
        self.minibatch_offset = 0
        self.minibatch_class = TRAIN
        self.last_minibatch = Bool(False)
        self.epoch_ended = Bool(False)
        self.train_ended = Bool(False)
        self.valid_ended = Bool(False)
        self.epoch_number = 0
        self.samples_served = 0
        self.shuffled_indices = Array()
        self.shuffle_limit = kwargs.get(
            "shuffle_limit", numpy.iinfo(numpy.uint32).max)
        self.prng = kwargs.get("prng", prng.get())
        self.normalizer = normalization.factory(
            kwargs.get("normalization_type", "none"),
            **kwargs.get("normalization_parameters", {}))
        # ensemble training subsets: the CLI's model-independent override
        # (root.common.ensemble.train_ratio) mirrors the reference's
        # --ensemble-train size:ratio flag; per-loader kwarg wins
        from ..config import root
        self.train_ratio = float(kwargs.get(
            "train_ratio",
            root.common.ensemble.get("train_ratio", 1.0) or 1.0))
        self.has_labels = True
        self.labels_mapping = {}
        self.raw_minibatch_labels = []
        self._global_offset = 0
        self.failed_minibatches = []
        self.testing = bool(kwargs.get("testing", False))

    def init_unpickled(self):
        super().init_unpickled()
        self.pending_minibatches_ = collections.defaultdict(list)
        # attached by MinibatchPrefetcher (transient: a restored
        # workflow re-attaches through StandardWorkflow.initialize)
        self.prefetcher_ = None
        self.prefetch_staged_ = None

    def __setstate__(self, state):
        # snapshots written before the valid_ended Bool existed must still
        # restore (forward-compat migration)
        state.setdefault("valid_ended", Bool(False))
        super().__setstate__(state)

    # -- derived sizes -------------------------------------------------------
    @property
    def total_samples(self):
        return sum(self.class_lengths)

    @property
    def effective_train_length(self):
        return int(self.class_lengths[TRAIN] * self.train_ratio)

    @property
    def effective_total(self):
        return (self.class_lengths[TEST] + self.class_lengths[VALID] +
                self.effective_train_length)

    def class_of_offset(self, offset):
        """Which class the (1-based end) offset falls in."""
        for cls in (TEST, VALID, TRAIN):
            if offset <= self.class_end_offsets[cls] and \
                    self.class_lengths[cls]:
                return cls
        return TRAIN

    # -- ILoader interface ---------------------------------------------------
    #: methods every concrete loader must implement (reference ILoader,
    #: verified at initialize by veles_tpu.verified.verify_contract)
    CONTRACT = ("load_data", "create_minibatch_data", "fill_minibatch")

    def load_data(self):
        raise NotImplementedError

    def create_minibatch_data(self):
        raise NotImplementedError

    def fill_minibatch(self):
        raise NotImplementedError

    def fill_indices(self, start_offset, count):
        """Copy shuffled indices into minibatch_indices; return True when
        the data gather is device-side (reference base.py:736-744)."""
        self.minibatch_indices.map_write()[:count] = \
            self.shuffled_indices[start_offset:start_offset + count]
        return False

    # -- lifecycle -----------------------------------------------------------
    def initialize(self, **kwargs):
        from ..verified import verify_contract
        verify_contract(self, Loader)
        super().initialize(**kwargs)
        self.load_data()
        if sum(self.class_lengths) == 0:
            raise LoaderError("empty dataset")
        offset = 0
        for cls in (TEST, VALID, TRAIN):
            offset += self.class_lengths[cls]
            self.class_end_offsets[cls] = offset
        self.max_minibatch_size = min(self.max_minibatch_size,
                                      max(self.class_lengths))
        self.minibatch_labels.reset(
            numpy.zeros(self.max_minibatch_size, self.LABEL_DTYPE)
            if self.has_labels else None)
        self.minibatch_indices.reset(
            numpy.zeros(self.max_minibatch_size, self.INDEX_DTYPE))
        self.raw_minibatch_labels = [None] * self.max_minibatch_size
        self.create_minibatch_data()
        if not self.minibatch_data:
            raise LoaderError(
                "minibatch_data MUST be initialized in "
                "create_minibatch_data()")
        restored = getattr(self.workflow, "restored_from_snapshot", False)
        if not restored or self.testing:
            self.analyze_dataset()
            self.shuffle()
            self._global_offset = 0
        else:
            # normalizer state and shuffle order came back with the
            # snapshot; re-analyzing would double-accumulate — only
            # re-apply the restored state to the reloaded raw data
            self.prepare_restored_dataset()

    def run(self):
        """Serve one minibatch (standalone mode).  With a
        MinibatchPrefetcher attached this whole method runs ahead on a
        worker thread and run() merely installs the next ready item."""
        self.serve_next_minibatch(None)
        # standalone: the minibatch is consumed synchronously, so it is no
        # longer outstanding when the epoch flags update
        self.pending_minibatches_.pop(None, None)
        self._on_successful_serve()

    # -- serving -------------------------------------------------------------
    def shuffle(self):
        """Shuffle the train segment only (reference base.py:711-724)."""
        if not self.shuffled_indices:
            self.shuffled_indices.mem = numpy.arange(
                self.total_samples, dtype=self.INDEX_DTYPE)
        if self.shuffle_limit <= 0 or self.class_lengths[TRAIN] == 0:
            return
        self.shuffle_limit -= 1
        self.prng.shuffle(
            self.shuffled_indices.map_write()[self.class_end_offsets[VALID]:])

    def _advance_global_offset(self):
        """Next (end_offset, size) pair; wraps into a new epoch."""
        if self._global_offset >= self.effective_total:
            self._global_offset = 0
            self.epoch_number += 1
            self.shuffle()
        cls = self.class_of_offset(self._global_offset + 1)
        size = min(self.max_minibatch_size,
                   self._class_end(cls) - self._global_offset)
        self._global_offset += size
        return self._global_offset, size

    def serve_next_minibatch(self, slave_id=None):
        try:
            minibatch_def = self.failed_minibatches.pop()
        except IndexError:
            minibatch_def = self._advance_global_offset()
        self.pending_minibatches_[slave_id].append(minibatch_def)
        self.minibatch_offset, self.minibatch_size = minibatch_def
        self.minibatch_class = self.class_of_offset(self.minibatch_offset)
        if self.fill_indices(self.minibatch_offset - self.minibatch_size,
                             self.minibatch_size):
            return
        self.fill_minibatch()
        self.normalize_minibatch()
        self.map_minibatch_labels()
        if self.minibatch_size < self.max_minibatch_size:
            self.minibatch_data.map_write()[self.minibatch_size:] = 0
            if self.has_labels:
                self.minibatch_labels.map_write()[self.minibatch_size:] = -1
            self.minibatch_indices.map_write()[self.minibatch_size:] = -1

    def _class_end(self, cls):
        if cls == TRAIN:
            return (self.class_end_offsets[VALID] +
                    self.effective_train_length)
        return self.class_end_offsets[cls]

    def _on_successful_serve(self):
        self.samples_served += self.minibatch_size
        # Flags fire only when no minibatch is pending or requeued
        # (reference base.py:863-871) — otherwise a dropped slave's job
        # would leak into the next epoch's accounting.  The class boundary
        # is judged at the *generator's* position, not the just-completed
        # job's offset, so out-of-order slave completions still close the
        # class once the final job drains.
        outstanding = (len(self.failed_minibatches) +
                       sum(len(v) for v in
                           self.pending_minibatches_.values()))
        if outstanding:
            self.last_minibatch <<= False
            self.train_ended <<= False
            self.valid_ended <<= False
            self.epoch_ended <<= False
            return
        cls = self.class_of_offset(self._global_offset)
        done = self._global_offset >= self._class_end(cls)
        self.last_minibatch <<= done
        self.train_ended <<= done and cls == TRAIN
        self.valid_ended <<= done and cls == VALID
        # epoch ends once the last class with samples completes
        last_cls = TRAIN if self.class_lengths[TRAIN] else (
            VALID if self.class_lengths[VALID] else TEST)
        self.epoch_ended <<= done and cls == last_cls

    @property
    def class_ended(self):
        return bool(self.last_minibatch)

    # -- normalization analysis (reference base.py:755-800) ------------------
    def analyze_dataset(self):
        if self.class_lengths[TRAIN] == 0:
            return
        if isinstance(self.normalizer, normalization.StatelessNormalizer):
            self.normalizer.analyze(self.minibatch_data.mem)
            return
        saved = (self._global_offset, self.minibatch_offset,
                 self.minibatch_size, self.minibatch_class)
        self.shuffled_indices.mem = numpy.arange(
            self.total_samples, dtype=self.INDEX_DTYPE)
        offset = self.class_end_offsets[VALID]
        end = self.class_end_offsets[TRAIN]
        while offset < end:
            size = min(self.max_minibatch_size, end - offset)
            self.minibatch_offset, self.minibatch_size = offset + size, size
            self.minibatch_indices.map_write()[:size] = \
                self.shuffled_indices[offset:offset + size]
            self.fill_minibatch()
            self.normalizer.analyze(
                self.minibatch_data.map_read()[:size])
            offset += size
        (self._global_offset, self.minibatch_offset,
         self.minibatch_size, self.minibatch_class) = saved

    def prepare_restored_dataset(self):
        """Re-apply restored normalizer state after a snapshot restore
        (loaders that bake normalization into a resident dataset
        override)."""

    def normalize_minibatch(self):
        self.normalizer.normalize(
            self.minibatch_data.map_write()[:self.minibatch_size])

    def materialize_minibatch(self):
        """Ensure minibatch_data/minibatch_labels hold the CURRENT
        minibatch host-side.  Host-path loaders already do; loaders whose
        gather is deferred into a consumer's jitted step (FullBatch under
        link_fused_gather) override to fill on demand.  Host-side
        consumers (MinibatchesSaver, ImageSaver, debuggers) call this
        before reading the Arrays."""

    def map_minibatch_labels(self):
        if not self.has_labels:
            return
        mem = self.minibatch_labels.map_write()
        for i, raw in enumerate(
                self.raw_minibatch_labels[:self.minibatch_size]):
            if raw is None:
                continue
            mem[i] = self.labels_mapping.setdefault(
                raw, len(self.labels_mapping))

    # -- IDistributable (master serves indices only, base.py:631-663) --------
    def generate_data_for_master(self):
        return True

    def generate_data_for_slave(self, slave=None):
        self.serve_next_minibatch(getattr(slave, "id", slave))
        data = {"indices":
                numpy.array(self.minibatch_indices[:self.minibatch_size])}
        for attr in ("minibatch_class", "minibatch_size", "minibatch_offset",
                     "epoch_number"):
            data[attr] = getattr(self, attr)
        return data

    def apply_data_from_master(self, data):
        for attr in ("minibatch_class", "minibatch_size", "minibatch_offset",
                     "epoch_number"):
            setattr(self, attr, data[attr])
        self.last_minibatch <<= False
        self.epoch_ended <<= False
        self.train_ended <<= False
        self.valid_ended <<= False
        indices = data["indices"]
        if indices.size != self.minibatch_size:
            raise LoaderError("minibatch size mismatch")
        if not self.shuffled_indices:
            self.shuffled_indices.mem = numpy.arange(
                self.total_samples, dtype=self.INDEX_DTYPE)
        self.shuffled_indices.map_write()[
            self.minibatch_offset - self.minibatch_size:
            self.minibatch_offset] = indices
        self.serve_from_applied_indices()

    def serve_from_applied_indices(self):
        """Slave-side gather for the indices the master assigned."""
        if self.fill_indices(self.minibatch_offset - self.minibatch_size,
                             self.minibatch_size):
            return
        self.fill_minibatch()
        self.normalize_minibatch()
        self.map_minibatch_labels()

    def apply_data_from_slave(self, data, slave=None):
        sid = getattr(slave, "id", slave)
        try:
            self.minibatch_offset, self.minibatch_size = \
                self.pending_minibatches_[sid].pop()
        except (KeyError, IndexError):
            raise LoaderError("no pending minibatch for slave %s" % sid)
        self.minibatch_class = self.class_of_offset(self.minibatch_offset)
        self._on_successful_serve()

    def drop_slave(self, slave=None):
        sid = getattr(slave, "id", slave)
        if sid in self.pending_minibatches_:
            self.failed_minibatches.extend(self.pending_minibatches_[sid])
            del self.pending_minibatches_[sid]

    @property
    def has_data_for_slave(self):
        return (not self.class_ended) or len(self.failed_minibatches) > 0

    # -- IResultProvider -----------------------------------------------------
    def get_metric_values(self):
        return {"Total epochs": self.epoch_number}
