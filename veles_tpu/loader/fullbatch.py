"""FullBatchLoader: the whole dataset resident in device HBM.

TPU-native re-design of /root/reference/veles/loader/fullbatch.py (:79; GPU
residency with OOM fallback :158-196; on-device minibatch gather kernel
``ocl/fullbatch_loader.cl`` / ``cuda/fullbatch_loader.cu``).  The reference
gathers minibatches on-device with a hand-written kernel walking
``shuffled_indices``; on TPU the same operation is one ``jnp.take`` inside a
jitted gather — XLA lowers it to an efficient dynamic-gather and fuses the
dtype cast.  Normalization is applied to the resident dataset once at
initialize (train-statistics analyze pass first), so the per-step path is
pure gather.
"""

import numpy

from ..memory import Array
from .. import normalization
from .base import Loader, TRAIN, VALID

#: row-band size for the cast+normalize pass (bounds the transient)
CAST_CHUNK_BYTES = 64 << 20


def cast_normalized(arr, dtype, normalizer, chunk_bytes=CAST_CHUNK_BYTES):
    """Cast the dataset Array ``arr`` to ``dtype`` and bake ``normalizer``
    in WITHOUT a second full-size copy: a same-dtype dataset is
    normalized in place, band by band; a dtype change allocates the
    destination exactly once and converts row bands through a small
    transient.  Every normalizer transforms rows independently, so
    banding is bit-exact vs the whole-array pass.  Returns the resident
    ndarray (also assigned back to ``arr.mem``)."""
    src = arr.map_write()
    apply = not isinstance(normalizer, normalization.NoneNormalizer)
    dtype = numpy.dtype(dtype)
    row_bytes = max(int(src[:1].nbytes), 1) if len(src) else 1
    rows = max(1, int(chunk_bytes) // row_bytes)
    if src.dtype == dtype:
        if apply:
            for i in range(0, len(src), rows):
                normalizer.normalize(src[i:i + rows])
        arr.mem = src
        return src
    dst = numpy.empty(src.shape, dtype)
    for i in range(0, len(src), rows):
        band = src[i:i + rows].astype(dtype)
        if apply:
            normalizer.normalize(band)
        dst[i:i + rows] = band
    arr.mem = dst
    return dst


class FullBatchLoader(Loader):
    """Dataset-as-one-Array loader with device-side gather.

    Subclasses implement ``load_data()`` filling ``original_data`` (and
    ``original_labels`` when ``has_labels``) plus ``class_lengths``.
    """

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.original_data = Array(shallow_pickle=True)
        self.original_labels = []
        self.force_numpy = bool(kwargs.get("force_numpy", False))
        self._dtype = kwargs.get("dtype", numpy.float32)
        # set by FusedTrainStep.link_fused_gather: indices only, the
        # device gather happens inside the consumer's jitted step
        self.defer_device_gather = False

    def create_minibatch_data(self):
        self.minibatch_data.reset(numpy.zeros(
            (self.max_minibatch_size,) + self.original_data.shape[1:],
            self._dtype))

    def fill_minibatch(self):
        """Host twin of the device gather (numpy path + analysis pass)."""
        idx = self.minibatch_indices.map_read()[:self.minibatch_size]
        self.minibatch_data.map_write()[:self.minibatch_size] = \
            self.original_data[idx]
        if self.has_labels:
            for i, sample_idx in enumerate(idx):
                self.raw_minibatch_labels[i] = \
                    self.original_labels[sample_idx]

    # -- device path ---------------------------------------------------------
    def initialize(self, device=None, **kwargs):
        super().initialize(device=device, **kwargs)
        self.device = device
        self._use_device = (device is not None and device.exists and
                            not self.force_numpy)
        if self._use_device:
            self._device_init()

    def analyze_dataset(self):
        """Analyze train statistics, then bake normalization into the
        resident dataset so the hot path is gather-only."""
        if self.class_lengths[TRAIN] and not isinstance(
                self.normalizer, normalization.StatelessNormalizer):
            train = self.original_data.map_read()[
                self.class_end_offsets[VALID]:self.class_end_offsets[TRAIN]]
            self.normalizer.analyze(train.astype(numpy.float64))
        else:
            self.normalizer.analyze(self.original_data.mem)
        self.prepare_restored_dataset()

    def prepare_restored_dataset(self):
        """Bake the (current or restored) normalizer state into the
        resident dataset and build the dense label table."""
        cast_normalized(self.original_data, self._dtype, self.normalizer)
        # labels → dense int mapping once, host-side
        if self.has_labels:
            self._dense_labels = numpy.zeros(len(self.original_labels),
                                             self.LABEL_DTYPE)
            for i, raw in enumerate(self.original_labels):
                self._dense_labels[i] = self.labels_mapping.setdefault(
                    raw, len(self.labels_mapping))

    def _gather_sources(self):
        """(resident device source, destination Array) pairs for the jitted
        gather — the single point subclasses extend."""
        import jax
        pairs = [(self.original_data.devmem, self.minibatch_data)]
        if self.has_labels:
            pairs.append((jax.device_put(self._dense_labels),
                          self.minibatch_labels))
        return pairs

    def _device_init(self):
        """Build ONE jitted gather over the declared sources (uploads stay
        resident in HBM; XLA fuses the per-source takes)."""
        if self.defer_device_gather:
            # the consumer (FusedTrainStep.link_fused_gather) gathers
            # inside its own jitted step — building the standalone gather
            # here would only duplicate the label table in HBM
            return
        import jax
        import jax.numpy as jnp
        pairs = self._gather_sources()
        # sources are ARGUMENTS, not closure captures: a closed-over
        # jax.Array is baked into the HLO as a literal constant, which
        # bloats the executable by the whole dataset (and overflows remote
        # compile transports); as arguments they stay HBM-resident buffers
        # the executable merely reads
        self._gather_sources_ = tuple(s for s, _ in pairs)
        self._gather_targets_ = [t for _, t in pairs]

        @jax.jit
        def gather(sources, idx):
            return tuple(jnp.take(src, idx, axis=0) for src in sources)
        self._gather_ = gather

    def fill_indices(self, start_offset, count):
        super().fill_indices(start_offset, count)
        if not getattr(self, "_use_device", False):
            return False
        idx = numpy.zeros(self.max_minibatch_size, self.INDEX_DTYPE)
        idx[:count] = self.shuffled_indices[start_offset:start_offset + count]
        if count < self.max_minibatch_size:
            idx[count:] = idx[0]  # pad with a valid index; masked downstream
        self._padded_indices_ = idx
        if self.defer_device_gather:
            return True  # consumer gathers inside its own jitted step
        for target, val in zip(self._gather_targets_,
                               self._gather_(self._gather_sources_, idx),
                               strict=True):
            target.devmem = val
        return True

    def normalize_minibatch(self):
        pass  # already baked into the resident dataset

    def materialize_minibatch(self):
        if not self.defer_device_gather and self._use_device:
            # device gather ran; pull is lazy via Array.map_read
            return
        if self.defer_device_gather:
            self.fill_minibatch()
            self.map_minibatch_labels()

    def map_minibatch_labels(self):
        if not self.has_labels:
            return
        idx = self.minibatch_indices.map_read()[:self.minibatch_size]
        self.minibatch_labels.map_write()[:self.minibatch_size] = \
            self._dense_labels[idx]


class FullBatchLoaderMSE(FullBatchLoader):
    """FullBatch variant with regression targets (reference
    fullbatch.py:467-563)."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.original_targets = Array(shallow_pickle=True)
        self.minibatch_targets = Array()
        self.has_labels = False
        self.targets_normalizer = normalization.factory(
            kwargs.get("target_normalization_type", "none"),
            **kwargs.get("target_normalization_parameters", {}))

    def create_minibatch_data(self):
        super().create_minibatch_data()
        self.minibatch_targets.reset(numpy.zeros(
            (self.max_minibatch_size,) + self.original_targets.shape[1:],
            self._dtype))

    def analyze_dataset(self):
        self.targets_normalizer.analyze(
            self.original_targets.map_read().astype(self._dtype))
        super().analyze_dataset()

    def prepare_restored_dataset(self):
        super().prepare_restored_dataset()
        cast_normalized(self.original_targets, self._dtype,
                        self.targets_normalizer)

    def _gather_sources(self):
        return [(self.original_data.devmem, self.minibatch_data),
                (self.original_targets.devmem, self.minibatch_targets)]

    def fill_minibatch(self):
        idx = self.minibatch_indices.map_read()[:self.minibatch_size]
        self.minibatch_data.map_write()[:self.minibatch_size] = \
            self.original_data[idx]
        self.minibatch_targets.map_write()[:self.minibatch_size] = \
            self.original_targets[idx]
