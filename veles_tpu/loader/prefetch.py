"""MinibatchPrefetcher: overlap host minibatch preparation with device
compute on the per-step training path.

The synchronous per-step loop runs ``loader.run() -> device_put ->
step.run()`` strictly serially, so the accelerator idles for the whole
host prepare+transfer on every minibatch (the ``data_wait`` phase the
StepProfiler measures).  VELES's own master-slave design pipelined
minibatch serving against compute; this is the standalone-mode
equivalent: a worker thread serves minibatches ``depth`` steps ahead
into a bounded queue, issues ``jax.device_put`` for each one (the H2D
copy overlaps the previous step's compute under JAX async dispatch),
and the consumer merely installs the next ready snapshot.

**Twin serving** keeps the semantics exact without duplicating any
loader logic: the worker drives a shadow *twin* of the loader — same
class, same ``__dict__`` (so the generator state is SHARED by
reference: ``prng``, ``shuffled_indices``, ``failed_minibatches``,
``labels_mapping``, class geometry) — but with private minibatch
Arrays, epoch-flag Bools and counters, so the worker never writes a
surface the consumer might concurrently read.  Each production calls
the loader's own, unmodified ``run()`` on the twin (index advance,
requeue pop, ``fill_minibatch``, normalization, label mapping, epoch
flags) and snapshots the result into an immutable item; consumption
installs the snapshot into the real loader — identical minibatch
order, identical shuffles, identical flag edges, one step later in
wall-clock only.

Guarantees:

- ``prefetch_depth = 0`` (or `attach` returning None) leaves the
  loader byte-for-byte on today's synchronous path;
- the shuffled minibatch sequence, failed-minibatch requeue
  (`loader/base.py` ``failed_minibatches``) and epoch metrics are
  identical to the synchronous path (asserted by
  ``tests/test_prefetch.py``);
- master/slave index serving still works: the first distributed call
  (``generate_data_for_slave`` / ``apply_data_from_master``) detaches
  the prefetcher and falls back to synchronous serving — the
  distributed protocol already pipelines at the job level;
- worker exceptions re-raise on the consumer thread (original
  traceback chained);
- ``stop()`` joins the worker without losing queued minibatches (they
  are consumed first on restart); the workflow-finish hook stops the
  worker so no thread outlives ``Workflow.run()``.
"""

import logging
import queue as queue_mod
import threading
import time
import weakref

import numpy

from ..config import root
from ..memory import Array
from ..mutable import Bool
from ..observability.registry import REGISTRY

logger = logging.getLogger("prefetch")

#: per-minibatch output surfaces the twin gets private copies of
_OUT_ARRAYS = ("minibatch_data", "minibatch_labels", "minibatch_indices",
               "minibatch_targets")
_OUT_FLAGS = ("last_minibatch", "epoch_ended", "train_ended", "valid_ended")
#: instance-dict wrappers that must never leak onto the twin
_WRAPPED = ("run", "stop", "generate_data_for_slave",
            "apply_data_from_master")
#: how long blocked queue ops sleep before re-checking stop/failure
_POLL_S = 0.05


class PrefetchError(RuntimeError):
    """The prefetch worker died and the original exception object was
    already delivered once — raised on any further serve attempt."""


def _worker_main(ref, stop_evt):
    """Worker thread entry.  Holds only a WEAK reference between
    cycles: a run-abandoned workflow (built, stepped a few times,
    dropped) must stay garbage-collectable — a strong ref here would
    pin the whole unit graph and keep the thread alive forever.  When
    the prefetcher is collected the worker exits on its next wake-up."""
    idle = 0
    while not stop_evt.is_set():
        self = ref()
        if self is None:
            return
        try:
            idle = self._work_once(idle)
        except BaseException as exc:  # noqa: BLE001 — re-raised at consume
            self._failure = exc
            return
        del self


class _Item:
    """One prefetched minibatch: everything the synchronous path would
    have left on the loader after ``run()``."""

    __slots__ = ("offset", "size", "cls", "epoch", "served",
                 "global_offset", "flags", "arrays", "raw_labels",
                 "padded", "staged")


class MinibatchPrefetcher:
    """Background producer for one loader's standalone serving path.

    Constructing one attaches it (mirrors StepProfiler); use
    :meth:`attach` to honor the ``prefetch_depth`` knob and loader
    capability in one call.  ``stage_to_device`` issues
    ``jax.device_put`` (optionally onto ``sharding`` — the step's batch
    sharding) from the worker so the transfer overlaps compute;
    without it items carry host copies only.
    """

    @classmethod
    def attach(cls, loader, depth=None, **kwargs):
        """Attach per config; returns None (no-op) when ``depth`` <= 0
        or the loader opts out (``supports_prefetch = False``)."""
        if depth is None:
            depth = int(root.common.loader.get("prefetch_depth", 2) or 0)
        if depth <= 0:
            return None
        if not getattr(loader, "supports_prefetch", True):
            logger.debug("%s opts out of prefetching", loader)
            return None
        existing = getattr(loader, "prefetcher_", None)
        if existing is not None:
            existing.detach()
        return cls(loader, depth=depth, **kwargs)

    def __init__(self, loader, depth=2, stage_to_device=True,
                 sharding=None, registry=None):
        if depth <= 0:
            raise ValueError("depth must be >= 1 (use attach() for the "
                             "0-disables-prefetch convention)")
        self._loader = loader
        self.depth = int(depth)
        self._stage = bool(stage_to_device)
        self._sharding = sharding
        self._queue = queue_mod.Queue(maxsize=self.depth)
        self._carry = None        # produced but not yet enqueued (stop())
        self._thread = None
        self._stop_evt = threading.Event()
        self._failure = None
        self._lock = threading.Lock()   # worker lifecycle transitions
        self.produced = 0
        self.consumed = 0
        self.restarts = 0
        self.wait_s = 0.0         # consumer time blocked on the queue
        reg = registry or REGISTRY
        lbl = {"loader": getattr(loader, "name", type(loader).__name__)}
        self._g_queue = reg.gauge(
            "veles_loader_prefetch_queue", "Prefetched minibatches ready",
            ("loader",)).labels(**lbl)
        self._c_items = reg.counter(
            "veles_loader_prefetch_items_total",
            "Minibatches served through the prefetch queue",
            ("loader",)).labels(**lbl)
        self._c_wait = reg.counter(
            "veles_loader_prefetch_wait_seconds_total",
            "Consumer time blocked waiting on the prefetch queue",
            ("loader",)).labels(**lbl)
        self._twin = self._make_twin()
        self._install_wrappers()
        loader.prefetcher_ = self

    # -- twin ----------------------------------------------------------------
    def _make_twin(self):
        """A worker-private serving view of the loader: shared generator
        state, private output surfaces."""
        ld = self._loader
        twin = object.__new__(type(ld))
        state = dict(ld.__dict__)
        for name in _WRAPPED:
            state.pop(name, None)   # never inherit instance wrappers
        twin.__dict__.update(state)
        import collections
        twin.pending_minibatches_ = collections.defaultdict(list)
        for name in _OUT_ARRAYS:
            arr = getattr(ld, name, None)
            if not isinstance(arr, Array):
                continue
            fresh = Array()
            host = arr.mem
            if host is None and arr:
                host = arr.map_read()
            if host is not None:
                fresh.reset(numpy.array(host, copy=True))
            setattr(twin, name, fresh)
        for name in _OUT_FLAGS:
            flag = getattr(ld, name, None)
            if isinstance(flag, Bool):
                setattr(twin, name, Bool(bool(flag)))
        twin.raw_minibatch_labels = list(ld.raw_minibatch_labels)
        # FullBatch device gather: its jitted gather writes through
        # ``_gather_targets_`` — retarget the loader's Arrays onto the
        # twin's private ones (sources and the jit itself stay shared)
        targets = getattr(ld, "_gather_targets_", None)
        if targets is not None:
            remap = {id(getattr(ld, n, None)): getattr(twin, n)
                     for n in _OUT_ARRAYS if getattr(ld, n, None)
                     is not None}
            twin._gather_targets_ = [remap.get(id(a), a) for a in targets]
        return twin

    # -- wrappers ------------------------------------------------------------
    def _install_wrappers(self):
        ld = self._loader
        # pre-existing instance-level overrides (e.g. an outer
        # profiler's wrapper) must survive a detach round-trip
        self._origs = {name: ld.__dict__.get(name)
                       for name in _WRAPPED}

        def _run():
            return self._consume()

        def _stop():
            # workflow finished: join the worker (no leaked threads);
            # queued items survive for a subsequent run()
            self.stop()
            return type(ld).stop(ld)

        def _gdfs(slave=None):
            self.detach(reason="master-side slave serving")
            return type(ld).generate_data_for_slave(ld, slave)

        def _adfm(data):
            self.detach(reason="slave-side master serving")
            return type(ld).apply_data_from_master(ld, data)

        self._wrappers = {"run": _run, "stop": _stop,
                          "generate_data_for_slave": _gdfs,
                          "apply_data_from_master": _adfm}
        for fn in self._wrappers.values():
            # Pickleable.__getstate__ drops transient_ callables, so a
            # snapshot taken mid-run never tries to pickle the worker
            fn.transient_ = True
        for name, fn in self._wrappers.items():
            setattr(ld, name, fn)

    # -- production (worker thread) ------------------------------------------
    def _produce(self):
        tw = self._twin
        tw.run()    # the loader's own standalone serving logic, verbatim
        it = _Item()
        it.offset = tw.minibatch_offset
        it.size = tw.minibatch_size
        it.cls = tw.minibatch_class
        it.epoch = tw.epoch_number
        it.served = tw.samples_served
        it.global_offset = tw._global_offset
        it.flags = tuple(bool(getattr(tw, n)) for n in _OUT_FLAGS)
        it.raw_labels = (list(tw.raw_minibatch_labels[:it.size])
                         if tw.has_labels else None)
        idx = tw.minibatch_indices
        it.arrays = [("minibatch_indices",
                      numpy.array(idx.mem, copy=True)
                      if idx.mem is not None else None, None)]
        it.padded = it.staged = None
        deferred = (getattr(tw, "defer_device_gather", False) and
                    getattr(tw, "_use_device", False))
        if deferred:
            # gather-in-step path: the data never leaves HBM residency;
            # stage the *indices* (and the size scalar) instead so the
            # step's host work is one dict lookup
            it.padded = tw._padded_indices_
            if self._stage:
                import jax
                it.staged = (jax.device_put(it.padded),
                             jax.device_put(numpy.int32(it.size)))
        else:
            for name in ("minibatch_data", "minibatch_labels",
                         "minibatch_targets"):
                arr = getattr(tw, name, None)
                if not isinstance(arr, Array) or not arr:
                    continue
                it.arrays.append((name,) + self._snap(arr))
        return it

    def _snap(self, arr):
        """(host, device) snapshot of one output Array.  Device-fresh
        values (the fullbatch jitted gather's outputs — a new buffer per
        call) ride as-is; host-fresh values are copied out of the twin's
        reused buffer and, when staging, device_put so the H2D overlaps
        the in-flight step."""
        if arr._device_dirty_ and arr._devmem_ is not None:
            return None, arr._devmem_
        host = numpy.array(arr.mem, copy=True)
        if self._stage:
            import jax
            if self._sharding is not None:
                return None, jax.device_put(host, self._sharding)
            return None, jax.device_put(host)
        return host, None

    def _work_once(self, idle_polls):
        """One produce-or-enqueue cycle; returns the next idle count.
        The put timeout backs off while the consumer is away so an idle
        worker costs ~nothing."""
        if self._carry is None:
            self._carry = self._produce()
            self.produced += 1
        timeout = min(_POLL_S * (1 + idle_polls), 1.0)
        try:
            self._queue.put(self._carry, timeout=timeout)
        except queue_mod.Full:
            return idle_polls + 1
        self._carry = None
        return 0

    # -- consumption (main thread) -------------------------------------------
    def _ensure_worker(self):
        t = self._thread
        if t is not None and t.is_alive():
            return
        if self._failure is not None:
            # a dead worker's remaining queue drains, but it is never
            # restarted — the twin's serving state is suspect
            if self._queue.empty():
                self._reraise()
            return
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            if self.produced > 0:
                self.restarts += 1
            self._stop_evt = threading.Event()
            self._thread = threading.Thread(
                target=_worker_main,
                args=(weakref.ref(self), self._stop_evt), daemon=True,
                name="veles-prefetch-%s" % getattr(
                    self._loader, "name", "loader"))
            self._thread.start()

    def _reraise(self):
        exc, self._failure = self._failure, PrefetchError(
            "prefetch worker for %s already died" % self._loader)
        raise exc

    def _consume(self):
        self._ensure_worker()
        t0 = time.perf_counter()
        while True:
            try:
                it = self._queue.get(timeout=_POLL_S)
                break
            except queue_mod.Empty:
                if self._failure is not None:
                    self._reraise()
                self._ensure_worker()
        waited = time.perf_counter() - t0
        self.wait_s += waited
        self._c_wait.inc(waited)
        self._c_items.inc()
        self._g_queue.set(self._queue.qsize())
        self._install(it)

    def _install(self, it):
        ld = self._loader
        ld.minibatch_offset = it.offset
        ld.minibatch_size = it.size
        ld.minibatch_class = it.cls
        ld.epoch_number = it.epoch
        ld.samples_served = it.served
        ld._global_offset = it.global_offset
        for name, host, dev in it.arrays:
            arr = getattr(ld, name)
            if dev is not None:
                arr.devmem = dev        # host copy pulled lazily on read
            elif host is not None:
                arr.mem = host
        if it.raw_labels is not None:
            ld.raw_minibatch_labels[:len(it.raw_labels)] = it.raw_labels
        if it.padded is not None:
            ld._padded_indices_ = it.padded
        ld.prefetch_staged_ = it.staged
        # flags last: downstream Bool expressions must see a complete
        # minibatch when an edge callback fires
        for name, value in zip(_OUT_FLAGS, it.flags):
            flag = getattr(ld, name)
            flag <<= value
        self.consumed += 1

    # -- lifecycle -----------------------------------------------------------
    def stop(self):
        """Join the worker; queued items are kept and consumed first if
        serving resumes."""
        self._stop_evt.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=10)
        self._thread = None

    def detach(self, reason=None):
        """Restore the loader's synchronous serving path.  Not-yet-
        consumed lookahead is discarded; the generator resumes from the
        last *consumed* minibatch (exactly where the synchronous path
        would be)."""
        self.stop()
        ld = self._loader
        for name, fn in self._wrappers.items():
            if ld.__dict__.get(name) is fn:
                del ld.__dict__[name]
                orig = self._origs.get(name)
                if orig is not None:
                    ld.__dict__[name] = orig
        # the loader's _global_offset sits at the last CONSUMED
        # minibatch, so synchronous serving re-generates (never skips)
        # anything that was still queued; prng draws that the twin spent
        # on a lookahead shuffle are not rewound — a valid (possibly
        # different) permutation for the epoch in progress
        ld.prefetch_staged_ = None
        ld.prefetcher_ = None
        if reason:
            logger.debug("prefetcher for %s detached (%s)", ld, reason)

    def stats(self):
        return {"depth": self.depth,
                "produced": self.produced,
                "consumed": self.consumed,
                "queued": self._queue.qsize(),
                "restarts": int(self.restarts),
                "consumer_wait_s": round(self.wait_s, 4),
                "staging": bool(self._stage)}

    def __repr__(self):
        return ("<MinibatchPrefetcher depth=%d of %r (%d/%d "
                "produced/consumed)>" % (self.depth, self._loader,
                                         self.produced, self.consumed))
