"""StreamLoader: feed a running workflow from an external queue.

Re-creation of /root/reference/veles/zmq_loader.py (:74): the reference
fed a *trained, running* workflow from an external ZeroMQ queue (the
serving input path).  The TPU-native equivalent is transport-agnostic: a
thread-safe ``queue.Queue`` that any producer (the REST API, a socket
reader, test code) pushes ``(data, labels)`` batches into; the loader
blocks on it per run and serves each batch as one TEST-class minibatch.
"""

import queue

import numpy

from ..memory import Array
from .base import Loader, TEST


class StreamLoader(Loader):
    """Serves externally-pushed batches (TEST class, no epochs)."""

    MAPPING = "stream_loader"
    # run() blocks on an external producer and may stop the workflow —
    # serving it from a prefetch worker would race both side channels
    supports_prefetch = False

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.queue = kwargs.get("queue") or queue.Queue(
            maxsize=int(kwargs.get("maxsize", 64)))
        self.timeout = kwargs.get("timeout")  # None = block forever
        # give up after this many CONSECUTIVE timeouts (None = wait for
        # the producer forever — a dead producer then needs close());
        # guards workflows against producers that die without the
        # sentinel.  Meaningless without a finite poll timeout, so one
        # is derived when absent.
        self.max_timeouts = kwargs.get("max_timeouts")
        if self.max_timeouts is not None and self.timeout is None:
            self.timeout = 5.0
        self.sample_shape = tuple(kwargs.get("sample_shape", ()))
        self.finished = False
        self._consecutive_timeouts = 0

    def feed(self, data, labels=None):
        """Producer side: enqueue one batch."""
        self.queue.put((numpy.asarray(data, numpy.float32), labels))

    def close(self):
        """Producer side: no more batches — the next run() stops the
        workflow's loop."""
        self.queue.put(None)

    # -- Loader protocol overrides -------------------------------------------
    def load_data(self):
        if not self.sample_shape:
            raise ValueError("StreamLoader needs sample_shape=")
        # a nominal single-class length: real serving is unbounded
        self.class_lengths[TEST] = int(1e9)
        self.has_labels = False

    def create_minibatch_data(self):
        self.minibatch_data.reset(numpy.zeros(
            (self.max_minibatch_size,) + self.sample_shape,
            numpy.float32))

    def fill_minibatch(self):
        pass  # batches arrive pre-filled through feed()

    def analyze_dataset(self):
        pass  # no resident data to analyze

    def shuffle(self):
        pass

    def run(self):
        try:
            item = self.queue.get(timeout=self.timeout)
        except queue.Empty:
            # transient producer delay, NOT a shutdown: serve an empty
            # minibatch and stay alive (close()'s None sentinel — or
            # max_timeouts consecutive dry polls — terminates)
            self._consecutive_timeouts += 1
            if self.max_timeouts is not None and \
                    self._consecutive_timeouts >= self.max_timeouts:
                item = None
            else:
                self.minibatch_size = 0
                return
        else:
            self._consecutive_timeouts = 0
        if item is None:
            self.finished = True
            self.stopped = True
            if self._workflow is not None:
                self._workflow.stop()
            return
        data, labels = item
        n = len(data)
        if n > self.max_minibatch_size:
            raise ValueError("batch of %d exceeds minibatch_size %d" %
                             (n, self.max_minibatch_size))
        self.minibatch_size = n
        self.minibatch_class = TEST
        mem = self.minibatch_data.map_write()
        mem[:n] = data.reshape((n,) + self.sample_shape)
        if n < self.max_minibatch_size:
            mem[n:] = 0
        if labels is not None:
            self.minibatch_labels = Array(numpy.asarray(labels))
        self.samples_served += n
