"""HDFS text streaming via the WebHDFS REST gateway.

Re-creation of /root/reference/veles/loader/hdfs_loader.py
(HDFSTextLoader:48-70): the reference streamed text lines from HDFS in
fixed-size chunks through the snakebite RPC client.  That client (and
libhdfs) is a heavy external dependency; every HDFS deployment also
exposes the WebHDFS REST API, which speaks plain HTTP — so this build
talks WebHDFS with stdlib urllib only: dependency-free, and testable
against a stub HTTP server the same way the reference network stack was
tested in-process.

Protocol: ``GET {url}/webhdfs/v1{path}?op=GETFILESTATUS`` for stat,
``?op=OPEN`` (redirect-following) for content, ``?op=LISTSTATUS`` for
directory listings.
"""

import json
import urllib.parse
import urllib.request

from ..mutable import Bool
from ..units import Unit


class WebHdfsClient:
    """Minimal WebHDFS REST client (stdlib-only)."""

    def __init__(self, url, user=None, timeout=30.0):
        self.base = url.rstrip("/")
        self.user = user
        self.timeout = timeout

    def _url(self, path, op, **params):
        if not path.startswith("/"):
            path = "/" + path
        params["op"] = op
        if self.user:
            params["user.name"] = self.user
        return "%s/webhdfs/v1%s?%s" % (
            self.base, urllib.parse.quote(path),
            urllib.parse.urlencode(params))

    def status(self, path):
        with urllib.request.urlopen(self._url(path, "GETFILESTATUS"),
                                    timeout=self.timeout) as r:
            return json.load(r)["FileStatus"]

    def list(self, path):
        with urllib.request.urlopen(self._url(path, "LISTSTATUS"),
                                    timeout=self.timeout) as r:
            statuses = json.load(r)["FileStatuses"]["FileStatus"]
        return [s["pathSuffix"] for s in statuses]

    def text(self, path, encoding="utf-8"):
        """Iterate the file's lines (OPEN follows the datanode
        redirect automatically via urllib)."""
        with urllib.request.urlopen(self._url(path, "OPEN"),
                                    timeout=self.timeout) as r:
            tail = b""
            while True:
                block = r.read(1 << 16)
                if not block:
                    break
                tail += block
                *lines, tail = tail.split(b"\n")
                for line in lines:
                    yield line.decode(encoding)
            if tail:
                yield tail.decode(encoding)


class HdfsTextLoader(Unit):
    """Stream an HDFS text file in fixed-size line chunks.

    Each run() fills ``output`` with the next ``chunk`` lines (the
    final partial chunk sets ``chunk_size`` < chunk) and raises
    ``finished`` when the file is exhausted — the reference
    HDFSTextLoader contract."""

    MAPPING = "hdfs_text_loader"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.file_name = kwargs["file"]
        self.chunk_lines_number = int(kwargs.get("chunk", 1000))
        self.hdfs_client = kwargs.get("client") or WebHdfsClient(
            kwargs["url"], user=kwargs.get("user"),
            timeout=kwargs.get("timeout", 30.0))
        self.output = [""] * self.chunk_lines_number
        self.chunk_size = 0
        self.finished = Bool(False)
        self._generator = None

    def initialize(self, **kwargs):
        super().initialize(**kwargs)
        # stat first: a missing path fails loudly at initialize, not
        # midway through the stream (reference did the same, :62)
        self.file_status = self.hdfs_client.status(self.file_name)
        self._generator = self.hdfs_client.text(self.file_name)

    def run(self):
        assert not self.finished
        self.chunk_size = 0
        try:
            for i in range(self.chunk_lines_number):
                self.output[i] = next(self._generator)
                self.chunk_size += 1
        except StopIteration:
            self.finished <<= True
