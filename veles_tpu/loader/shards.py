"""ShardedBatchLoader: stream an on-disk sharded dataset through a
bounded read-ahead window — dataset size decoupled from host RAM.

On-disk layout (written by :func:`write_shards`)::

    index.json            format, class lengths, sample shape/dtype,
                          per-shard row counts
    shard-00000.npy       rows [0, r0) in dataset order [test|valid|train]
    shard-00001.npy       rows [r0, r0+r1) ...
    labels.npy            one label per sample (small; RAM-resident)

Only the *data* rows stream: labels and the index stay in RAM (they are
O(samples), not O(bytes)).  The loader keeps at most ``window_bytes`` of
decoded shards cached; eviction is Belady's rule — the permutation for
the whole epoch is known the moment ``shuffle()`` runs, so the shard
whose next use lies farthest in the future is always the one dropped.

Two shuffle modes:

- ``shuffle_mode="global"`` (default): the inherited
  :meth:`Loader.shuffle` permutes the train segment exactly like
  FullBatchLoader — the served minibatch stream is **bit-identical** to
  a FullBatchLoader over the same arrays whenever the normalizer
  coefficients agree (test-enforced).  Random global access means a
  window smaller than the dataset re-reads shards; correctness never
  depends on the window size.
- ``shuffle_mode="windowed"``: shard ORDER and rows within each shard
  are permuted instead — I/O stays sequential per shard and each shard
  is read exactly once per epoch, at the cost of stream parity with the
  global shuffle (still deterministic under the loader prng).

Normalization is applied per minibatch from the same analyze statistics
FullBatchLoader computes (train segment, float64, dataset order), so
restored snapshots resume with identical transforms.
"""

import bisect
import json
import os

import numpy

from .. import normalization
from .base import Loader, LoaderError, TRAIN, VALID

INDEX = "index.json"
LABELS = "labels.npy"
SHARD_FMT = "shard-%05d.npy"
FORMAT = 1


def write_shards(directory, data, labels=None, class_lengths=None,
                 rows_per_shard=None, shard_bytes=64 << 20):
    """Materialize an in-RAM dataset as a sharded on-disk dataset.

    ``data`` is the full ``[test|valid|train]``-ordered array (anything
    numpy can view row-wise); ``class_lengths`` the usual 3-list.  Shard
    size comes from ``rows_per_shard`` or a ``shard_bytes`` budget.
    Returns the index path."""
    data = numpy.asarray(data)
    if data.ndim < 1 or not len(data):
        raise ValueError("empty dataset")
    if class_lengths is None:
        class_lengths = [0, 0, len(data)]
    if sum(class_lengths) != len(data):
        raise ValueError("class_lengths %s != %d rows"
                         % (class_lengths, len(data)))
    if rows_per_shard is None:
        rows_per_shard = max(1, int(shard_bytes) // max(data[:1].nbytes, 1))
    os.makedirs(directory, exist_ok=True)
    shards = []
    for k, start in enumerate(range(0, len(data), rows_per_shard)):
        block = numpy.ascontiguousarray(data[start:start + rows_per_shard])
        name = SHARD_FMT % k
        numpy.save(os.path.join(directory, name), block)
        shards.append({"file": name, "rows": int(len(block))})
    if labels is not None:
        if len(labels) != len(data):
            raise ValueError("labels length mismatch")
        numpy.save(os.path.join(directory, LABELS), numpy.asarray(labels))
    index = {
        "format": FORMAT,
        "class_lengths": [int(c) for c in class_lengths],
        "sample_shape": [int(s) for s in data.shape[1:]],
        "dtype": data.dtype.str,
        "labels": LABELS if labels is not None else None,
        "shards": shards,
    }
    path = os.path.join(directory, INDEX)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(index, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


class ShardedBatchLoader(Loader):
    """Minibatches from an on-disk sharded dataset through a bounded
    shard window (``window_bytes``, default 256 MiB)."""

    MAPPING = "sharded_batch"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.path = kwargs["path"]
        self.window_bytes = int(kwargs.get("window_bytes", 256 << 20))
        self.shuffle_mode = kwargs.get("shuffle_mode", "global")
        if self.shuffle_mode not in ("global", "windowed"):
            raise ValueError("shuffle_mode must be global|windowed")
        self._dtype = kwargs.get("dtype", numpy.float32)
        self.original_labels = []

    def init_unpickled(self):
        super().init_unpickled()
        # ONE mutable holder for all window state: the prefetcher's
        # serving twin shares the loader's __dict__ shallowly, so
        # scalar counters would silently fork between the two views —
        # dicts are shared by reference and stay consistent
        self._window_ = {"cache": {}, "bytes": 0, "loads": 0,
                         "positions": None}

    # -- dataset geometry ----------------------------------------------------
    def load_data(self):
        with open(os.path.join(self.path, INDEX)) as f:
            index = json.load(f)
        if index.get("format") != FORMAT:
            raise LoaderError("unsupported shard index format: %r"
                              % index.get("format"))
        self._index = index
        self._shard_files = [s["file"] for s in index["shards"]]
        rows = [int(s["rows"]) for s in index["shards"]]
        starts = numpy.zeros(len(rows) + 1, numpy.int64)
        numpy.cumsum(rows, out=starts[1:])
        self._shard_starts = starts          # starts[k] .. starts[k+1]
        self.class_lengths = list(index["class_lengths"])
        if int(starts[-1]) != sum(self.class_lengths):
            raise LoaderError("index rows != class lengths")
        self._sample_shape = tuple(index["sample_shape"])
        self._raw_dtype = numpy.dtype(index["dtype"])
        if index.get("labels"):
            self.original_labels = list(
                numpy.load(os.path.join(self.path, index["labels"]),
                           allow_pickle=True))
            self.has_labels = True
        else:
            self.has_labels = False

    def create_minibatch_data(self):
        self.minibatch_data.reset(numpy.zeros(
            (self.max_minibatch_size,) + self._sample_shape, self._dtype))

    # -- the bounded shard window --------------------------------------------
    def _shard(self, k):
        w = self._window_
        block = w["cache"].get(k)
        if block is None:
            block = numpy.load(
                os.path.join(self.path, self._shard_files[k]))
            w["loads"] += 1
            w["cache"][k] = block
            w["bytes"] += block.nbytes
            self._evict(keep=k)
        return block

    def _evict(self, keep):
        """Shrink the window back under budget, dropping the cached
        shard whose next use is farthest away (Belady — the epoch's
        access sequence is fully known from ``shuffled_indices``)."""
        w = self._window_
        while w["bytes"] > self.window_bytes and len(w["cache"]) > 1:
            victim = max((s for s in w["cache"] if s != keep),
                         key=self._next_use, default=None)
            if victim is None:
                return
            w["bytes"] -= w["cache"][victim].nbytes
            del w["cache"][victim]

    def _next_use(self, shard):
        positions = self._use_positions().get(shard)
        if positions is None or not len(positions):
            return numpy.inf
        i = numpy.searchsorted(positions, self._global_offset)
        return numpy.inf if i == len(positions) else int(positions[i])

    def _use_positions(self):
        """shard id -> sorted serving positions for the current epoch's
        permutation (rebuilt whenever ``shuffle()`` reorders)."""
        if self._window_["positions"] is None:
            if not self.shuffled_indices:
                return {}   # analyze pass: sequential walk, any victim ok
            order = numpy.asarray(self.shuffled_indices.mem)
            sid = numpy.searchsorted(
                self._shard_starts, order, side="right") - 1
            self._window_["positions"] = {
                int(s): numpy.flatnonzero(sid == s)
                for s in numpy.unique(sid)}
        return self._window_["positions"]

    # -- serving -------------------------------------------------------------
    def shuffle(self):
        if self.shuffle_mode == "windowed" and self.shuffle_limit > 0 and \
                self.class_lengths[TRAIN]:
            self._windowed_shuffle()
        else:
            super().shuffle()
        self._window_["positions"] = None

    def _windowed_shuffle(self):
        """Permute shard ORDER and rows within each shard (train segment
        only): every shard is read exactly once per epoch, in sequence.
        Deterministic under the loader prng; NOT stream-identical to the
        global shuffle."""
        if not self.shuffled_indices:
            self.shuffled_indices.mem = numpy.arange(
                self.total_samples, dtype=self.INDEX_DTYPE)
        self.shuffle_limit -= 1
        lo = self.class_end_offsets[VALID]
        hi = self.class_end_offsets[TRAIN]
        starts = self._shard_starts
        groups = []
        for k in range(len(self._shard_files)):
            a, b = max(int(starts[k]), lo), min(int(starts[k + 1]), hi)
            if a < b:
                groups.append(numpy.arange(a, b, dtype=self.INDEX_DTYPE))
        order = numpy.arange(len(groups))
        self.prng.shuffle(order)
        out = []
        for g in order:
            rows = groups[g]
            self.prng.shuffle(rows)
            out.append(rows)
        self.shuffled_indices.map_write()[lo:hi] = numpy.concatenate(out)

    def fill_minibatch(self):
        idx = numpy.asarray(
            self.minibatch_indices.map_read()[:self.minibatch_size],
            numpy.int64)
        out = self.minibatch_data.map_write()
        sid = numpy.searchsorted(self._shard_starts, idx, side="right") - 1
        for s in numpy.unique(sid):
            block = self._shard(int(s))
            rows = numpy.flatnonzero(sid == s)
            out[rows] = block[idx[rows] - int(self._shard_starts[s])]

    # -- normalization / labels (FullBatchLoader-parity) ---------------------
    def analyze_dataset(self):
        """Same statistics FullBatchLoader computes — train segment,
        float64, dataset order — accumulated shard by shard."""
        if self.class_lengths[TRAIN] and not isinstance(
                self.normalizer, normalization.StatelessNormalizer):
            lo = self.class_end_offsets[VALID]
            hi = self.class_end_offsets[TRAIN]
            for k in range(len(self._shard_files)):
                a = max(int(self._shard_starts[k]), lo)
                b = min(int(self._shard_starts[k + 1]), hi)
                if a >= b:
                    continue
                block = self._shard(k)
                off = int(self._shard_starts[k])
                self.normalizer.analyze(
                    block[a - off:b - off].astype(numpy.float64))
        elif len(self._shard_files):
            self.normalizer.analyze(self._shard(0))
        self.prepare_restored_dataset()

    def prepare_restored_dataset(self):
        """Dense label table in DATASET order (identical id assignment
        to FullBatchLoader, which maps before shuffling)."""
        if self.has_labels:
            self._dense_labels = numpy.zeros(len(self.original_labels),
                                             self.LABEL_DTYPE)
            for i, raw in enumerate(self.original_labels):
                self._dense_labels[i] = self.labels_mapping.setdefault(
                    raw, len(self.labels_mapping))

    def map_minibatch_labels(self):
        if not self.has_labels:
            return
        idx = self.minibatch_indices.map_read()[:self.minibatch_size]
        self.minibatch_labels.map_write()[:self.minibatch_size] = \
            self._dense_labels[idx]

    # -- introspection -------------------------------------------------------
    @property
    def window_used_bytes(self):
        return self._window_["bytes"]

    @property
    def shard_loads(self):
        return self._window_["loads"]

    @property
    def shards_cached(self):
        return sorted(self._window_["cache"])

    def shard_of(self, sample):
        return bisect.bisect_right(self._shard_starts.tolist(), sample) - 1

    def get_metric_values(self):
        vals = super().get_metric_values()
        vals["Shard loads"] = self.shard_loads
        return vals
