"""InteractiveLoader: hand-feed a running workflow from code/REPL.

Re-creation of /root/reference/veles/loader/interactive.py (:57-127):
the reference blocked its workflow until the user ``feed()``-ed an
object — a numpy array, a text stream for ``numpy.loadtxt``, a file
path, or a URL — and served it as one minibatch, optionally deriving
normalization from a trained loader (``derive_from``).  Here it rides
the StreamLoader queue (the transport-agnostic serving input path), so
the same workflow can be driven from the shell (interaction.py) or a
notebook while keeping the normal unit protocol.  URL download is
delegated to the Downloader unit rather than re-implemented.
"""

import io
import os

import numpy

from .stream import StreamLoader


class InteractiveLoader(StreamLoader):
    """Serves objects fed interactively; each feed is one minibatch."""

    MAPPING = "interactive_loader"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self._loadtxt_kwargs = dict(kwargs.get("loadtxt_kwargs", {}))
        self._normalizer = None

    def derive_from(self, loader):
        """Copy the trained loader's normalization (and sample shape if
        unset), so interactive samples go through the same preprocessing
        the model was trained with (reference interactive.py:185-200)."""
        self._normalizer = getattr(loader, "normalizer", None)
        if not self.sample_shape:
            shape = getattr(loader, "minibatch_data", None)
            if shape is not None and shape.shape:
                self.sample_shape = tuple(shape.shape[1:])
        return self

    def feed(self, obj, labels=None):
        """Accepts a numpy array / nested list, a text file path, or an
        open text stream (numpy.loadtxt); single samples are promoted to
        a batch of one."""
        if isinstance(obj, str):
            if not os.path.exists(obj):
                raise ValueError(
                    "no such file: %r (URLs go through the Downloader "
                    "unit)" % obj)
            with open(obj) as f:
                obj = numpy.loadtxt(f, **self._loadtxt_kwargs)
        elif isinstance(obj, io.IOBase):
            obj = numpy.loadtxt(obj, **self._loadtxt_kwargs)
        arr = numpy.asarray(obj, numpy.float32)
        if self.sample_shape and arr.shape == tuple(self.sample_shape):
            arr = arr[None]  # single sample convenience
        if self._normalizer is not None:
            arr = arr.copy()
            self._normalizer.normalize(arr)
        super().feed(arr, labels)
