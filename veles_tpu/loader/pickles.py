"""Pickle / HDF5 / file-list loaders (reference veles/loader/pickles.py,
loader_hdf5.py, file_loader.py).

All feed the same HBM-resident FullBatch pipeline: host-side reading at
initialize, device gather per step.
"""

import os
import pickle

import numpy

from .base import TEST, VALID, TRAIN
from .fullbatch import FullBatchLoader


def _split_payload(payload):
    """(data, labels) from a pickle payload: tuple/list pair or a dict
    with data/labels keys."""
    if isinstance(payload, dict):
        return payload["data"], payload.get("labels")
    if isinstance(payload, (tuple, list)) and len(payload) == 2:
        return payload[0], payload[1]
    return payload, None


class PicklesLoader(FullBatchLoader):
    """Datasets from per-class pickle files (reference pickles.py).

    kwargs ``test_path``/``validation_path``/``train_path``: each a
    pickle of ``(data, labels)`` or ``{"data": ..., "labels": ...}``."""

    MAPPING = "pickles_loader"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.paths = {TEST: kwargs.get("test_path"),
                      VALID: kwargs.get("validation_path"),
                      TRAIN: kwargs.get("train_path")}

    def load_class(self, cls):
        path = self.paths[cls]
        if not path:
            return None, None
        with open(path, "rb") as f:
            return _split_payload(pickle.load(f))

    def load_data(self):
        chunks, labels = [], []
        for cls in (TEST, VALID, TRAIN):
            data, lab = self.load_class(cls)
            n = 0 if data is None else len(data)
            self.class_lengths[cls] = n
            if n:
                chunks.append(numpy.asarray(data, numpy.float32))
                if lab is not None:
                    labels.extend(list(lab))
        if not chunks:
            raise ValueError("no class path produced data")
        self.original_data.mem = numpy.concatenate(chunks)
        if labels:
            if len(labels) != len(self.original_data.mem):
                raise ValueError("labels/data length mismatch")
            self.original_labels = labels
        else:
            self.has_labels = False


class Hdf5Loader(FullBatchLoader):
    """Datasets from HDF5 files (reference loader_hdf5.py).

    kwargs ``test_path``/``validation_path``/``train_path``; dataset
    names via ``data_name``/``labels_name`` (default "data"/"labels")."""

    MAPPING = "hdf5_loader"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.paths = {TEST: kwargs.get("test_path"),
                      VALID: kwargs.get("validation_path"),
                      TRAIN: kwargs.get("train_path")}
        self.data_name = kwargs.get("data_name", "data")
        self.labels_name = kwargs.get("labels_name", "labels")

    def load_data(self):
        import h5py
        chunks, labels = [], []
        for cls in (TEST, VALID, TRAIN):
            path = self.paths[cls]
            if not path:
                self.class_lengths[cls] = 0
                continue
            with h5py.File(path, "r") as f:
                data = numpy.asarray(f[self.data_name], numpy.float32)
                self.class_lengths[cls] = len(data)
                chunks.append(data)
                if self.labels_name in f:
                    labels.extend(numpy.asarray(f[self.labels_name])
                                  .tolist())
        if not chunks:
            raise ValueError("no class path produced data")
        self.original_data.mem = numpy.concatenate(chunks)
        if labels:
            if len(labels) != len(self.original_data.mem):
                raise ValueError(
                    "labels/data length mismatch: some class files carry "
                    "a %r dataset and others do not" % self.labels_name)
            self.original_labels = labels
        else:
            self.has_labels = False


class FileListLoader(FullBatchLoader):
    """Numeric-array files listed per class (reference file_loader.py):
    each file is one ``.npy`` sample (or a batch when ``batched``)."""

    MAPPING = "file_list_loader"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.file_lists = {TEST: list(kwargs.get("test_files", ())),
                           VALID: list(kwargs.get("validation_files", ())),
                           TRAIN: list(kwargs.get("train_files", ()))}
        self.label_from = kwargs.get(
            "label_from", lambda path: os.path.basename(
                os.path.dirname(path)))

    def load_data(self):
        samples, labels = [], []
        for cls in (TEST, VALID, TRAIN):
            files = self.file_lists[cls]
            self.class_lengths[cls] = len(files)
            for path in files:
                samples.append(numpy.load(path).astype(numpy.float32))
                labels.append(self.label_from(path))
        if not samples:
            raise ValueError("no files listed")
        self.original_data.mem = numpy.stack(samples)
        self.original_labels = labels
