"""LMDB dataset loader, dependency-free (VERDICT r4 item 6).

The reference names LMDB as a workflow data source (caffe-style keyed
image databases, docs/source/manualrst_veles_workflow_creation.rst:99)
and reads it through the ``lmdb`` C binding.  That package is absent
here — but LMDB is a stable mmap'd B+tree format, so this module reads
the file format directly with stdlib ``mmap`` + ``struct``:

- ``LMDBFile``: read-only walker of an LMDB environment's main DB —
  meta-page selection by txnid, branch/leaf B+tree DFS, ``F_BIGDATA``
  overflow-page values.  Covers the on-disk format of LMDB 0.9.x
  (magic 0xBEEFC0DE, data version 1), 64-bit builds — what every
  caffe-era dataset uses.  Dupsort/DUPFIXED sub-databases are out of
  scope (datasets are plain key->value).
- ``LMDBLoader``: FullBatchLoader over one environment per class with a
  pluggable ``decode(key, value) -> (array, label)`` hook.  The default
  decodes this repo's fixture protocol (uint32 label + .npy payload,
  tools/make_lmdb_fixture.py); caffe Datum users supply their own hook.

Byte layout cross-checked against the LMDB source tree's struct
definitions (MDB_page/MDB_node/MDB_meta in mdb.c); the test fixture is
written by an independent minimal writer and read back by this reader.
"""

import io
import mmap
import os
import struct

import numpy

from .base import TEST, VALID, TRAIN
from .fullbatch import FullBatchLoader

MDB_MAGIC = 0xBEEFC0DE
MDB_VERSION = 1
P_INVALID = 0xFFFFFFFFFFFFFFFF

P_BRANCH = 0x01
P_LEAF = 0x02
P_OVERFLOW = 0x04
P_META = 0x08
P_LEAF2 = 0x20

F_BIGDATA = 0x01
F_SUBDATA = 0x02
F_DUPDATA = 0x04

PAGE_HDR = 16           # MDB_page header bytes
NODE_HDR = 8            # MDB_node header bytes
_META_DB = struct.Struct("<IHH5Q")          # MDB_db: 48 bytes
_META_HEAD = struct.Struct("<II2Q")         # magic, version, addr, mapsize


class LMDBFormatError(ValueError):
    pass


class LMDBFile:
    """Read-only view of an LMDB environment's main database."""

    def __init__(self, path):
        if os.path.isdir(path):
            path = os.path.join(path, "data.mdb")
        self.path = path
        self._f = open(path, "rb")
        try:
            self._mm = mmap.mmap(self._f.fileno(), 0,
                                 access=mmap.ACCESS_READ)
        except ValueError:
            self._f.close()
            raise
        try:
            m0 = self._read_meta(0, 4096)
            # meta page 1 sits at offset psize (known only after meta 0)
            m1 = self._read_meta(1, m0["psize"])
        except Exception:
            self.close()  # no fd/mapping leak on a corrupt file
            raise
        meta = m0 if m0["txnid"] >= m1["txnid"] else m1
        self.psize = meta["psize"]
        self.entries = meta["entries"]
        self.depth = meta["depth"]
        self._root = meta["root"]

    def close(self):
        self._mm.close()
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- low-level ------------------------------------------------------
    def _read_meta(self, which, psize):
        off = which * psize
        flags = struct.unpack_from("<H", self._mm, off + 10)[0]
        if not flags & P_META:
            raise LMDBFormatError("page %d is not a meta page" % which)
        off += PAGE_HDR
        magic, version, _addr, _mapsize = _META_HEAD.unpack_from(
            self._mm, off)
        if magic != MDB_MAGIC:
            raise LMDBFormatError("bad LMDB magic 0x%X" % magic)
        if version != MDB_VERSION:
            raise LMDBFormatError("unsupported LMDB data version %d"
                                  % version)
        off += _META_HEAD.size
        free_db = _META_DB.unpack_from(self._mm, off)
        main_db = _META_DB.unpack_from(self._mm, off + _META_DB.size)
        off += 2 * _META_DB.size
        _last_pg, txnid = struct.unpack_from("<2Q", self._mm, off)
        # md_pad of the free DB doubles as the env page size (mm_psize)
        return {"psize": free_db[0], "txnid": txnid,
                "depth": main_db[2], "entries": main_db[6],
                "root": main_db[7]}

    def _page(self, pgno):
        off = pgno * self.psize
        if off + PAGE_HDR > len(self._mm):
            raise LMDBFormatError("page %d beyond file end" % pgno)
        flags, lower = struct.unpack_from("<HH", self._mm, off + 10)
        return off, flags, lower

    def _node(self, page_off, ptr):
        lo, hi, flags, ksize = struct.unpack_from(
            "<4H", self._mm, page_off + ptr)
        key = self._mm[page_off + ptr + NODE_HDR:
                       page_off + ptr + NODE_HDR + ksize]
        return lo, hi, flags, ksize, key

    def _bytes(self, start, size):
        """Bounds-checked mmap read: a truncated data.mdb must fail
        loudly, never yield silently short values."""
        if start + size > len(self._mm):
            raise LMDBFormatError(
                "value [%d:%d] beyond file end (%d bytes) — truncated "
                "database?" % (start, start + size, len(self._mm)))
        return bytes(self._mm[start:start + size])

    def _leaf_value(self, page_off, ptr):
        lo, hi, flags, ksize, key = self._node(page_off, ptr)
        dsize = lo | (hi << 16)
        data_off = page_off + ptr + NODE_HDR + ksize
        if flags & (F_SUBDATA | F_DUPDATA):
            raise LMDBFormatError(
                "dupsort sub-database values are not supported")
        if flags & F_BIGDATA:
            (ov_pgno,) = struct.unpack_from("<Q", self._mm, data_off)
            ov_off, ov_flags, _ = self._page(ov_pgno)
            if not ov_flags & P_OVERFLOW:
                raise LMDBFormatError(
                    "pgno %d is not an overflow page" % ov_pgno)
            # data runs contiguously after the first page's header
            return bytes(key), self._bytes(ov_off + PAGE_HDR, dsize)
        return bytes(key), self._bytes(data_off, dsize)

    # -- iteration ------------------------------------------------------
    def items(self):
        """Yield (key, value) in key order via B+tree DFS."""
        if self._root == P_INVALID:
            return
        stack = [self._root]
        while stack:
            pgno = stack.pop()
            page_off, flags, lower = self._page(pgno)
            nkeys = (lower - PAGE_HDR) >> 1
            ptrs = struct.unpack_from("<%dH" % nkeys, self._mm,
                                      page_off + PAGE_HDR)
            if flags & P_LEAF2:
                raise LMDBFormatError("LEAF2 (dupfixed) not supported")
            if flags & P_BRANCH:
                children = []
                for ptr in ptrs:
                    lo, hi, nflags, _, _ = self._node(page_off, ptr)
                    children.append(lo | (hi << 16) | (nflags << 32))
                stack.extend(reversed(children))  # keep key order
            elif flags & P_LEAF:
                for ptr in ptrs:
                    yield self._leaf_value(page_off, ptr)
            else:
                raise LMDBFormatError(
                    "page %d has unexpected flags 0x%x" % (pgno, flags))

    def __len__(self):
        return self.entries


def default_decode(key, value):
    """This repo's fixture protocol: uint32 little-endian label, then a
    ``.npy`` payload (tools/make_lmdb_fixture.py writes it)."""
    (label,) = struct.unpack_from("<I", value)
    arr = numpy.load(io.BytesIO(value[4:]), allow_pickle=False)
    return arr, int(label)


class LMDBLoader(FullBatchLoader):
    """Keyed-image datasets straight from LMDB environments (the
    reference's caffe-style loader, manualrst_veles_workflow_creation
    .rst:99) — one environment (dir or data.mdb) per class via
    ``test_path``/``validation_path``/``train_path``, samples decoded
    by ``decode(key, value) -> (ndarray, label)``."""

    MAPPING = "lmdb_loader"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.paths = {TEST: kwargs.get("test_path"),
                      VALID: kwargs.get("validation_path"),
                      TRAIN: kwargs.get("train_path")}
        self.decode = kwargs.get("decode", default_decode)

    def load_data(self):
        samples, labels = [], []
        for cls in (TEST, VALID, TRAIN):
            path = self.paths[cls]
            if not path:
                self.class_lengths[cls] = 0
                continue
            n = 0
            with LMDBFile(path) as db:
                for key, value in db.items():
                    arr, label = self.decode(key, value)
                    samples.append(numpy.asarray(arr, numpy.float32))
                    labels.append(label)
                    n += 1
            self.class_lengths[cls] = n
        if not samples:
            raise ValueError("no LMDB path produced data")
        self.original_data.mem = numpy.stack(samples)
        labeled = sum(lab is not None for lab in labels)
        if labeled == len(labels):
            self.original_labels = labels
        elif labeled:
            # fail like the sibling loaders (pickles.py) do on partial
            # labels — a None mapped to its own label class would train
            # on corrupted targets silently
            raise ValueError(
                "decode returned labels for %d of %d samples; label "
                "all samples or none" % (labeled, len(labels)))
        else:
            self.has_labels = False
