"""RestfulLoader: an HTTP input path INTO a live workflow.

Re-creation of /root/reference/veles/loader/restful.py (:52-131) + the
loader half of restful_api.py: the reference batched concurrent HTTP
requests into one minibatch (flushing when full or when
``max_response_time`` elapsed), ran the workflow's own forward graph on
it, and answered every request with its output row.  This is distinct
from :class:`veles_tpu.restful_api.RESTfulAPI`, which serves a separate
jitted forward; the loader path exercises the LIVE workflow — its
normalization, its units, its observables.

Pieces:
- :class:`RestfulLoader` — StreamLoader whose producer is an embedded
  stdlib HTTP server; requests accumulate under a lock and flush to the
  workflow queue when a minibatch fills or the response timer fires.
- :class:`RestfulResponder` — the unit linked after the last forward;
  hands the output rows back to the waiting HTTP threads.

Protocol (same shape as the serving endpoint):
    POST /api {"input": [...sample...]}  → {"result": r, "output": [...]}
"""

import queue as queue_mod
import threading
from http.server import ThreadingHTTPServer

import numpy

from ..httpjson import JsonRequestHandler
from ..units import Unit
from .base import TEST
from .stream import StreamLoader


class _Request:
    __slots__ = ("sample", "event", "output", "error")

    def __init__(self, sample):
        self.sample = sample
        self.event = threading.Event()
        self.output = None
        self.error = None


class RestfulLoader(StreamLoader):
    """Feed the workflow from HTTP requests, batched reference-style."""

    MAPPING = "restful_loader"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.port = int(kwargs.get("port", 0))
        self.max_response_time = float(
            kwargs.get("max_response_time", 0.05))
        if self.max_response_time < 0:
            raise ValueError("max_response_time must be >= 0")
        self.response_timeout = float(
            kwargs.get("response_timeout", 30.0))
        self._pending = []
        self._plock = threading.Lock()
        self._inflight = []
        self._httpd = None
        self._flusher = None
        self._closing = threading.Event()

    # -- lifecycle -----------------------------------------------------------
    def initialize(self, device=None, **kwargs):
        super().initialize(device=device, **kwargs)
        if self._httpd is None:
            handler = type("Handler", (_Handler,), {"loader": self})
            self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port),
                                              handler)
            self.port = self._httpd.server_address[1]
            threading.Thread(target=self._httpd.serve_forever,
                             daemon=True, name="restful-loader").start()
            self._flusher = threading.Thread(
                target=self._flush_loop, daemon=True,
                name="restful-loader-flush")
            self._flusher.start()

    def close(self):
        self._closing.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        super().close()

    # -- request intake ------------------------------------------------------
    def submit(self, sample):
        """HTTP thread: enqueue one sample, return its pending request.

        Shape is validated HERE, before the sample can reach the batch:
        one malformed request must get its own 400, never a stack/
        reshape error on the workflow or flusher thread."""
        arr = numpy.asarray(sample, numpy.float32)
        want = tuple(self.sample_shape)
        if arr.shape != want:
            if arr.size != int(numpy.prod(want)):
                raise ValueError(
                    "sample shape %s does not match the workflow's %s"
                    % (arr.shape, want))
            arr = arr.reshape(want)
        req = _Request(arr)
        with self._plock:
            self._pending.append(req)
            if len(self._pending) >= self.max_minibatch_size:
                self._flush_locked()
        return req

    def _flush_loop(self):
        while not self._closing.wait(self.max_response_time):
            with self._plock:
                self._flush_locked()

    def _flush_locked(self):
        if not self._pending:
            return
        batch, self._pending = self._pending, []
        data = numpy.stack([r.sample for r in batch])
        self.queue.put((data, batch))

    # -- Loader protocol -----------------------------------------------------
    def run(self):
        self._inflight = []
        try:
            item = self.queue.get(timeout=self.timeout)
        except queue_mod.Empty:
            self.minibatch_size = 0
            return
        if item is None:  # close(): stop the workflow loop
            self.finished = True
            self.stopped = True
            if self._workflow is not None:
                self._workflow.stop()
            return
        data, reqs = item
        n = len(data)
        self.minibatch_size = n
        self.minibatch_class = TEST
        mem = self.minibatch_data.map_write()
        mem[:n] = data.reshape((n,) + tuple(self.sample_shape))
        if n < self.max_minibatch_size:
            mem[n:] = 0
        self._inflight = list(reqs)
        self.samples_served += n

    def respond(self, outputs):
        """Responder side: route output row i to waiting request i."""
        reqs, self._inflight = self._inflight, []
        outputs = numpy.asarray(outputs)
        for i, req in enumerate(reqs):
            if i < len(outputs):
                req.output = outputs[i]
            else:
                req.error = "workflow produced no output row"
            req.event.set()


class RestfulResponder(Unit):
    """Link after the last forward: flushes its ``input`` rows back to
    the loader's waiting HTTP requests."""

    MAPPING = "restful_responder"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.loader = kwargs.get("loader")
        self.input = None  # link_attrs from the last forward's output

    def run(self):
        out = self.input.map_read() if hasattr(self.input, "map_read") \
            else numpy.asarray(self.input)
        self.loader.respond(numpy.asarray(out)[:self.loader.minibatch_size])


class _Handler(JsonRequestHandler):
    loader = None

    def do_POST(self):
        if self.path != "/api":
            self.send_json(404, {"error": "not found"})
            return
        try:
            sample = self.read_input_payload()
            req = self.loader.submit(sample)
        except Exception as e:  # client errors must get a JSON answer
            self.send_json(400, {"error": str(e)})
            return
        if not req.event.wait(self.loader.response_timeout):
            self.send_json(504, {"error": "workflow response timeout"})
            return
        if req.error:
            self.send_json(500, {"error": req.error})
            return
        out = numpy.asarray(req.output)
        result = int(out.argmax()) if out.ndim == 1 and len(out) > 1 \
            else out.tolist()
        self.send_json(200, {"result": result, "output": out.tolist()})
