"""Image pipeline: decode/resize/augment images into the HBM fullbatch.

TPU-native re-design of /root/reference/veles/loader/image.py (~1300 LoC
of per-minibatch PIL work) + fullbatch_image.py.  The reference decoded
and transformed images per minibatch on the host; on TPU the host would
then fight the device for the input pipeline, so the design decodes and
augments ONCE at initialize into the resident FullBatch dataset (HBM),
and the per-step path stays a fused device gather.  The capability
surface kept: scale (factor or fixed target, aspect-preserving with
background fill), center crop, horizontal mirror expansion, grayscale/
RGB channel handling, background color, and the
``get_keys``/``get_image_data``/``get_image_label`` subclass protocol
(reference IImageLoader, image.py:83-104).
"""

import os

import numpy

from .base import TEST, VALID, TRAIN
from .fullbatch import FullBatchLoader


class ImageLoader(FullBatchLoader):
    """FullBatch loader whose samples come from decoded images.

    kwargs:
      scale: float factor or (height, width) target size;
      maintain_aspect: letterbox into the target with background fill
        (reference scale_maintain_aspect_ratio);
      crop: (height, width) center crop after scaling;
      mirror: False | True — True EXPANDS the train set with horizontally
        flipped copies (the static-dataset equivalent of the reference's
        per-epoch "random" mirror);
      grayscale: collapse to one channel;
      background_color: RGB fill for letterboxing.
    """

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.scale = kwargs.get("scale", 1.0)
        self.maintain_aspect = bool(kwargs.get("maintain_aspect", True))
        self.crop = kwargs.get("crop")
        self.mirror = kwargs.get("mirror", False)
        self.grayscale = bool(kwargs.get("grayscale", False))
        self.background_color = tuple(
            kwargs.get("background_color", (0, 0, 0)))

    # -- subclass protocol (reference IImageLoader) --------------------------
    def get_keys(self, class_index):
        """Image keys (e.g. paths) for TEST/VALID/TRAIN."""
        raise NotImplementedError

    def get_image_label(self, key):
        raise NotImplementedError

    def get_image_data(self, key):
        """Decode one image to an HxWxC uint8/float array."""
        from PIL import Image
        with Image.open(key) as img:
            return numpy.asarray(img.convert(
                "L" if self.grayscale else "RGB"))

    # -- transforms ----------------------------------------------------------
    def transform_image(self, data):
        """scale → crop → channel handling; returns float32 HxWxC."""
        from PIL import Image
        if data.ndim == 2:
            data = data[:, :, None]
        img = data
        if self.scale != 1.0:
            if isinstance(self.scale, (tuple, list)):
                th, tw = self.scale
            else:
                th = int(round(img.shape[0] * self.scale))
                tw = int(round(img.shape[1] * self.scale))
            pil = Image.fromarray(img.squeeze(-1) if img.shape[-1] == 1
                                  else img)
            if self.maintain_aspect:
                ratio = min(th / img.shape[0], tw / img.shape[1])
                nh = max(1, int(round(img.shape[0] * ratio)))
                nw = max(1, int(round(img.shape[1] * ratio)))
                pil = pil.resize((nw, nh), Image.BILINEAR)
                bg = self.background_color
                canvas = Image.new(
                    pil.mode, (tw, th),
                    bg[0] if pil.mode == "L" else bg)
                canvas.paste(pil, ((tw - nw) // 2, (th - nh) // 2))
                pil = canvas
            else:
                pil = pil.resize((tw, th), Image.BILINEAR)
            img = numpy.asarray(pil)
            if img.ndim == 2:
                img = img[:, :, None]
        if self.crop is not None:
            ch, cw = self.crop
            oy = max((img.shape[0] - ch) // 2, 0)
            ox = max((img.shape[1] - cw) // 2, 0)
            img = img[oy:oy + ch, ox:ox + cw]
        return numpy.asarray(img, numpy.float32)

    # -- FullBatch integration -----------------------------------------------
    def load_data(self):
        data_per_class = {}
        labels_per_class = {}
        for cls in (TEST, VALID, TRAIN):
            keys = list(self.get_keys(cls))
            samples, labels = [], []
            for key in keys:
                samples.append(self.transform_image(
                    self.get_image_data(key)))
                labels.append(self.get_image_label(key))
            if cls == TRAIN and self.mirror and samples:
                samples += [s[:, ::-1].copy() for s in samples]
                labels += list(labels)
            data_per_class[cls] = samples
            labels_per_class[cls] = labels
        all_samples = (data_per_class[TEST] + data_per_class[VALID] +
                       data_per_class[TRAIN])
        if not all_samples:
            raise ValueError("no images found by get_keys")
        shapes = {s.shape for s in all_samples}
        if len(shapes) != 1:
            raise ValueError(
                "images produce differing sample shapes %s — set scale=(h, "
                "w) or crop to normalize them" % sorted(shapes))
        self.original_data.mem = numpy.stack(all_samples)
        self.original_labels = (labels_per_class[TEST] +
                                labels_per_class[VALID] +
                                labels_per_class[TRAIN])
        for cls in (TEST, VALID, TRAIN):
            self.class_lengths[cls] = len(data_per_class[cls])


class FileImageLoader(ImageLoader):
    """Directory-tree image loader: labels from subdirectory names.

    (reference file_image.py / FileListImageLoader role.)

    kwargs ``test_paths``/``validation_paths``/``train_paths``: lists of
    directories whose immediate subdirectories name the labels, e.g.
    ``train/cat/1.png``; flat directories label every file with the
    directory's own basename."""

    MAPPING = "file_image_loader"
    EXTENSIONS = (".png", ".jpg", ".jpeg", ".bmp", ".ppm", ".gif")

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.class_paths = {
            TEST: list(kwargs.get("test_paths", ())),
            VALID: list(kwargs.get("validation_paths", ())),
            TRAIN: list(kwargs.get("train_paths", ())),
        }

    def get_keys(self, class_index):
        keys = []
        for base in self.class_paths[class_index]:
            for dirpath, _dirs, files in sorted(os.walk(base)):
                for fname in sorted(files):
                    if os.path.splitext(fname)[1].lower() in \
                            self.EXTENSIONS:
                        keys.append(os.path.join(dirpath, fname))
        return keys

    def get_image_label(self, key):
        return os.path.basename(os.path.dirname(key))
