"""Image pipeline: decode/resize/augment images into the HBM fullbatch.

TPU-native re-design of /root/reference/veles/loader/image.py (~1300 LoC
of per-minibatch PIL/OpenCV work) + file_image.py + fullbatch_image.py +
image_mse.py.  The reference decoded and transformed images per minibatch
on the host; on TPU the host would then fight the device for the input
pipeline, so the design decodes and augments ONCE at initialize into the
resident FullBatch dataset (HBM), and the per-step path stays a fused
device gather.  The capability surface kept from the reference:

- scale (factor or fixed target), aspect-preserving letterbox with
  background fill from a color OR a background image
  (image.py:139-146,316-331);
- rotations: a tuple of angles (radians) — every sample is emitted once
  per rotation, the reference's samples_inflation (image.py:136,294-313);
- center crop, plus ``crop_number`` > 1 multi-crops per image with
  ``smart_crop`` (deterministic even spread) or seeded-random offsets
  (image.py:138,254-280);
- mirror: False | True (expand the train set with flipped copies) |
  "random" (seeded per-sample coin flip, the static-dataset equivalent
  of the reference's per-epoch random mirror); both TRAIN only
  (image.py:283-291);
- grayscale / color_space conversions (RGB, L/GRAY, HSV, YCbCr — PIL
  modes; reference used OpenCV spaces, image.py:116-127);
- ``add_sobel`` extra edge-magnitude channel (image.py:131,384,433);
- the ``get_keys``/``get_image_data``/``get_image_label`` subclass
  protocol (reference IImageLoader, image.py:83-104);
- directory scanning with include/ignore regex filters
  (file_loader.py:54-100, file_image.py:53-177);
- image→image MSE target pairs (image_mse.py:47-126): every input
  transform is replayed identically on the target image so augmented
  pairs stay aligned.

Mean subtraction (reference path_to_mean) is handled by the normalizer
family (veles_tpu/normalization.py) rather than inside the loader.
"""

import math
import os
import re

import numpy

from .base import TEST, VALID, TRAIN
from .fullbatch import FullBatchLoader, FullBatchLoaderMSE

_SOBEL_X = numpy.array([[-1, 0, 1], [-2, 0, 2], [-1, 0, 1]],
                       numpy.float32)
_SOBEL_Y = _SOBEL_X.T


def sobel_magnitude(gray):
    """|∇I| of a 2-D array via the 3x3 Sobel pair (edge-replicated)."""
    padded = numpy.pad(gray.astype(numpy.float32), 1, mode="edge")
    gx = numpy.zeros_like(gray, numpy.float32)
    gy = numpy.zeros_like(gray, numpy.float32)
    for dy in range(3):
        for dx in range(3):
            window = padded[dy:dy + gray.shape[0], dx:dx + gray.shape[1]]
            gx += _SOBEL_X[dy, dx] * window
            gy += _SOBEL_Y[dy, dx] * window
    return numpy.hypot(gx, gy)


class ImageTransformer:
    """The shared decode→scale→rotate→crop→channels pipeline + the
    variant fan-out (rotations x crops), reused by the plain and the
    MSE image loaders."""

    def _init_transforms(self, kwargs):
        self.scale = kwargs.get("scale", 1.0)
        self.maintain_aspect = bool(kwargs.get("maintain_aspect", True))
        self.crop = kwargs.get("crop")
        self.crop_number = int(kwargs.get("crop_number", 1))
        self.smart_crop = bool(kwargs.get("smart_crop", True))
        self.mirror = kwargs.get("mirror", False)
        self.rotations = tuple(kwargs.get("rotations", (0.0,)))
        for rot in self.rotations:
            if not 0.0 <= float(rot) < 2 * math.pi:
                raise ValueError("rotations must be radians in [0, 2π): %r"
                                 % (rot,))
        if self.crop_number < 1:
            raise ValueError("crop_number must be >= 1")
        if self.crop_number > 1 and self.crop is None:
            raise ValueError("crop_number > 1 requires crop=(h, w)")
        if self.mirror not in (False, True, "random"):
            raise ValueError("mirror must be False, True or 'random'")
        self.grayscale = bool(kwargs.get("grayscale", False))
        self.color_space = kwargs.get(
            "color_space", "L" if self.grayscale else "RGB")
        if self.color_space == "GRAY":
            self.color_space = "L"
        self.add_sobel = bool(kwargs.get("add_sobel", False))
        self.background_color = tuple(
            kwargs.get("background_color", (0, 0, 0)))
        self._background_image = kwargs.get("background_image")

    @property
    def samples_inflation(self):
        """How many samples each source image becomes (before mirror
        expansion): one per rotation per crop (reference image.py:311)."""
        return len(self.rotations) * self.crop_number

    # -- decoding ------------------------------------------------------------
    def decode_image(self, key):
        """Decode one image file to HxWxC in ``color_space``."""
        from PIL import Image
        with Image.open(key) as img:
            arr = numpy.asarray(img.convert(self.color_space))
        return arr

    def _pil_of(self, arr):
        from PIL import Image
        if arr.ndim == 3 and arr.shape[-1] == 1:
            arr = arr[..., 0]
        return Image.fromarray(arr)

    def _background_canvas(self, mode, size):
        from PIL import Image
        if self._background_image is not None:
            bg = self._background_image
            if isinstance(bg, str):
                with Image.open(bg) as img:
                    bg = numpy.asarray(img.convert(self.color_space))
                self._background_image = bg
            canvas = self._pil_of(numpy.asarray(bg)).convert(mode)
            return canvas.resize(size, Image.BILINEAR)
        bg = self.background_color
        return Image.new(mode, size, bg[0] if mode == "L" else bg)

    # -- per-image transform chain -------------------------------------------
    def scale_image(self, data):
        """factor/target scale, optional aspect-preserving letterbox."""
        from PIL import Image
        if data.ndim == 2:
            data = data[:, :, None]
        img = data
        if self.scale == 1.0:
            return img
        if isinstance(self.scale, (tuple, list)):
            th, tw = self.scale
        else:
            th = int(round(img.shape[0] * self.scale))
            tw = int(round(img.shape[1] * self.scale))
        pil = self._pil_of(img)
        if self.maintain_aspect:
            ratio = min(th / img.shape[0], tw / img.shape[1])
            nh = max(1, int(round(img.shape[0] * ratio)))
            nw = max(1, int(round(img.shape[1] * ratio)))
            pil = pil.resize((nw, nh), Image.BILINEAR)
            canvas = self._background_canvas(pil.mode, (tw, th))
            canvas.paste(pil, ((tw - nw) // 2, (th - nh) // 2))
            pil = canvas
        else:
            pil = pil.resize((tw, th), Image.BILINEAR)
        out = numpy.asarray(pil)
        return out[:, :, None] if out.ndim == 2 else out

    def rotate_image(self, img, angle):
        """Rotate about the center (radians, CCW), background-filled,
        same output shape (reference rotations semantics)."""
        if not angle:
            return img
        from PIL import Image
        pil = self._pil_of(img)
        bg = self.background_color
        fill = bg[0] if pil.mode == "L" else tuple(bg)
        pil = pil.rotate(math.degrees(angle), resample=Image.BILINEAR,
                         expand=False, fillcolor=fill)
        out = numpy.asarray(pil)
        return out[:, :, None] if out.ndim == 2 else out

    def _crop_offsets(self, shape):
        """Offsets of the crop windows: center for 1; an even spread
        (smart) or seeded-random positions for crop_number > 1."""
        ch, cw = self.crop
        maxy = max(shape[0] - ch, 0)
        maxx = max(shape[1] - cw, 0)
        n = self.crop_number
        if n == 1:
            return [(maxy // 2, maxx // 2)]
        if self.smart_crop:
            # deterministic even coverage along both axes
            return [(int(round(i * maxy / (n - 1))),
                     int(round(i * maxx / (n - 1)))) for i in range(n)]
        return [(int(self.prng.randint(0, maxy + 1)),
                 int(self.prng.randint(0, maxx + 1))) for _ in range(n)]

    def crop_image(self, img, offset):
        ch, cw = self.crop
        oy, ox = offset
        return img[oy:oy + ch, ox:ox + cw]

    def finalize_channels(self, img):
        """Optional sobel channel; float32 output."""
        img = numpy.asarray(img, numpy.float32)
        if img.ndim == 2:
            img = img[:, :, None]
        if self.add_sobel:
            gray = img.mean(axis=-1) if img.shape[-1] > 1 else img[..., 0]
            img = numpy.concatenate(
                [img, sobel_magnitude(gray)[:, :, None]], axis=-1)
        return img

    def image_variants(self, data):
        """All (rotation x crop) variants of one decoded image, in a
        deterministic order: rotations outer, crops inner."""
        scaled = self.scale_image(numpy.asarray(data))
        variants = []
        for angle in self.rotations:
            rotated = self.rotate_image(scaled, angle)
            if self.crop is not None:
                for off in self._crop_offsets(rotated.shape):
                    variants.append(
                        self.finalize_channels(
                            self.crop_image(rotated, off)))
            else:
                variants.append(self.finalize_channels(rotated))
        return variants

    # -- dataset assembly ----------------------------------------------------
    def build_class_samples(self, keys, get_data, paired_get_data=None):
        """Decode+transform every key; returns (samples, counts[,
        paired samples]) where counts[i] is how many variants key i
        produced.  ``paired_get_data`` (MSE targets) replays the exact
        transform sequence on the paired image — crop offsets are
        re-seeded per key so input and target crops align."""
        samples, paired, counts = [], [], []
        for key in keys:
            if paired_get_data is not None and not self.smart_crop and \
                    self.crop_number > 1:
                state = self.prng.state
            variants = self.image_variants(get_data(key))
            samples.extend(variants)
            counts.append(len(variants))
            if paired_get_data is not None:
                if not self.smart_crop and self.crop_number > 1:
                    self.prng.state = state
                paired.extend(self.image_variants(paired_get_data(key)))
        if paired_get_data is not None:
            return samples, counts, paired
        return samples, counts

    def apply_mirror(self, cls, samples, labels, paired=None):
        """mirror=True: append flipped copies; mirror="random": seeded
        per-sample coin flip in place.  Both modes are TRAIN only —
        flipped eval samples would distort validation metrics."""
        if self.mirror is True and cls == TRAIN:
            samples += [s[:, ::-1].copy() for s in samples]
            labels += list(labels)
            if paired is not None:
                paired += [t[:, ::-1].copy() for t in paired]
        elif self.mirror == "random" and cls == TRAIN:
            # TRAIN-only for the same reason as mirror=True: randomly
            # flipped eval samples would distort validation metrics
            for i in range(len(samples)):
                if self.prng.randint(0, 2):
                    samples[i] = samples[i][:, ::-1].copy()
                    if paired is not None:
                        paired[i] = paired[i][:, ::-1].copy()


class ImageLoader(ImageTransformer, FullBatchLoader):
    """FullBatch loader whose samples come from decoded images.

    See the module docstring for the transform surface; subclasses
    implement the reference IImageLoader protocol: ``get_keys``,
    ``get_image_label``, and optionally ``get_image_data``."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self._init_transforms(kwargs)

    # -- subclass protocol (reference IImageLoader) --------------------------
    def get_keys(self, class_index):
        """Image keys (e.g. paths) for TEST/VALID/TRAIN."""
        raise NotImplementedError

    def get_image_label(self, key):
        raise NotImplementedError

    def get_image_data(self, key):
        """Decode one image to an HxWxC array (``color_space``)."""
        return self.decode_image(key)

    def transform_image(self, data):
        """First (rotation, crop) variant — kept for API compatibility."""
        return self.image_variants(data)[0]

    # -- FullBatch integration -----------------------------------------------
    def load_data(self):
        data_per_class = {}
        labels_per_class = {}
        for cls in (TEST, VALID, TRAIN):
            keys = list(self.get_keys(cls))
            samples, counts = self.build_class_samples(
                keys, self.get_image_data)
            labels = []
            for key, n in zip(keys, counts):
                labels += [self.get_image_label(key)] * n
            self.apply_mirror(cls, samples, labels)
            data_per_class[cls] = samples
            labels_per_class[cls] = labels
        all_samples = (data_per_class[TEST] + data_per_class[VALID] +
                       data_per_class[TRAIN])
        if not all_samples:
            raise ValueError("no images found by get_keys")
        shapes = {s.shape for s in all_samples}
        if len(shapes) != 1:
            raise ValueError(
                "images produce differing sample shapes %s — set scale=(h, "
                "w) or crop to normalize them" % sorted(shapes))
        self.original_data.mem = numpy.stack(all_samples)
        self.original_labels = (labels_per_class[TEST] +
                                labels_per_class[VALID] +
                                labels_per_class[TRAIN])
        for cls in (TEST, VALID, TRAIN):
            self.class_lengths[cls] = len(data_per_class[cls])


class ImageLoaderMSE(ImageTransformer, FullBatchLoaderMSE):
    """Image→image regression pairs (reference image_mse.py): inputs and
    targets are decoded images, and every augmentation (scale, rotation,
    crops, mirror) is replayed identically on the target so the pairs
    stay aligned.  Subclasses implement ``get_keys``/``get_image_data``
    plus ``get_target_key`` (input key → target key) or override
    ``get_target_data`` directly."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self._init_transforms(kwargs)

    def get_keys(self, class_index):
        raise NotImplementedError

    def get_target_key(self, key):
        """Map an input image key to its target image key."""
        raise NotImplementedError

    def get_image_data(self, key):
        return self.decode_image(key)

    def get_target_data(self, key):
        return self.decode_image(self.get_target_key(key))

    def load_data(self):
        data_per_class = {}
        targets_per_class = {}
        for cls in (TEST, VALID, TRAIN):
            keys = list(self.get_keys(cls))
            samples, _counts, targets = self.build_class_samples(
                keys, self.get_image_data,
                paired_get_data=self.get_target_data)
            labels = []  # MSE: labels unused
            self.apply_mirror(cls, samples, labels, paired=targets)
            data_per_class[cls] = samples
            targets_per_class[cls] = targets
        all_samples = (data_per_class[TEST] + data_per_class[VALID] +
                       data_per_class[TRAIN])
        if not all_samples:
            raise ValueError("no images found by get_keys")
        self.original_data.mem = numpy.stack(all_samples)
        self.original_targets.mem = numpy.stack(
            targets_per_class[TEST] + targets_per_class[VALID] +
            targets_per_class[TRAIN])
        for cls in (TEST, VALID, TRAIN):
            self.class_lengths[cls] = len(data_per_class[cls])


class FileFilterMixin:
    """Directory scanning with include/ignore regex filters (reference
    file_loader.py FileFilter: included_files/ignored_files)."""

    EXTENSIONS = (".png", ".jpg", ".jpeg", ".bmp", ".ppm", ".gif")

    def _init_filters(self, kwargs):
        self._included = [re.compile(p) for p in
                          kwargs.get("included_files", (".*",))]
        self._ignored = [re.compile(p) for p in
                         kwargs.get("ignored_files", ())]

    def is_valid_filename(self, fname):
        if os.path.splitext(fname)[1].lower() not in self.EXTENSIONS:
            return False
        if not any(p.match(fname) for p in self._included):
            return False
        return not any(p.match(fname) for p in self._ignored)

    def scan_directories(self, bases):
        keys = []
        for base in bases:
            for dirpath, _dirs, files in sorted(os.walk(base)):
                for fname in sorted(files):
                    if self.is_valid_filename(fname):
                        keys.append(os.path.join(dirpath, fname))
        return keys


class FileImageLoader(FileFilterMixin, ImageLoader):
    """Directory-tree image loader: labels from subdirectory names.

    (reference file_image.py FileImageLoader/AutoLabelFileImageLoader.)

    kwargs ``test_paths``/``validation_paths``/``train_paths``: lists of
    directories whose immediate subdirectories name the labels, e.g.
    ``train/cat/1.png``; flat directories label every file with the
    directory's own basename.  ``included_files``/``ignored_files``:
    regex lists filtering filenames (reference FileFilter)."""

    MAPPING = "file_image_loader"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self._init_filters(kwargs)
        self.class_paths = {
            TEST: list(kwargs.get("test_paths", ())),
            VALID: list(kwargs.get("validation_paths", ())),
            TRAIN: list(kwargs.get("train_paths", ())),
        }

    def get_keys(self, class_index):
        return self.scan_directories(self.class_paths[class_index])

    def get_image_label(self, key):
        return os.path.basename(os.path.dirname(key))


class FileImageLoaderMSE(FileFilterMixin, ImageLoaderMSE):
    """Directory-scanning image→image pairs: inputs under
    ``*_paths``, targets resolved by basename under ``target_paths``
    (reference file_image.py FileImageLoaderMSEMixin: target_paths +
    basename matching)."""

    MAPPING = "file_image_loader_mse"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self._init_filters(kwargs)
        self.class_paths = {
            TEST: list(kwargs.get("test_paths", ())),
            VALID: list(kwargs.get("validation_paths", ())),
            TRAIN: list(kwargs.get("train_paths", ())),
        }
        self.target_paths = list(kwargs.get("target_paths", ()))
        self._target_index = None

    def get_keys(self, class_index):
        return self.scan_directories(self.class_paths[class_index])

    def get_target_key(self, key):
        if self._target_index is None:
            self._target_index = {}
            for tkey in self.scan_directories(self.target_paths):
                self._target_index[os.path.basename(tkey)] = tkey
        base = os.path.basename(key)
        try:
            return self._target_index[base]
        except KeyError:
            raise ValueError("no target image named %r under %s"
                             % (base, self.target_paths))
