"""Audio loader: WAV files → fixed-length windows in the HBM fullbatch.

Re-creation of /root/reference/veles/loader/libsndfile_loader.py: the
reference decoded audio through a ctypes libsndfile binding
(libsndfile.py) into normalized float arrays and scanned directories via
FileListLoaderBase.  This build decodes with the stdlib ``wave`` module
(PCM 8/16/32-bit WAV — the formats the reference's own tests used);
libsndfile's exotic formats (FLAC/OGG) are environment-gated the same
way LMDB is.  Decoded tracks are sliced into fixed ``window`` sample
frames so the result is a normal FullBatch dataset: resident in HBM,
gather-in-step, any Znicz topology on top.
"""

import os

import numpy

from .base import TEST, VALID, TRAIN
from .fullbatch import FullBatchLoader
from .image import FileFilterMixin


def decode_wav(path, mono=True):
    """Decode a PCM WAV file to float32 in [-1, 1]; (frames, channels)
    or (frames,) when ``mono`` mixes the channels down."""
    import wave
    with wave.open(path, "rb") as w:
        n_channels = w.getnchannels()
        width = w.getsampwidth()
        frames = w.readframes(w.getnframes())
        rate = w.getframerate()
    if width == 1:      # unsigned 8-bit
        data = (numpy.frombuffer(frames, numpy.uint8).astype(numpy.float32)
                - 128.0) / 128.0
    elif width == 2:    # signed 16-bit
        data = numpy.frombuffer(frames, "<i2").astype(
            numpy.float32) / 32768.0
    elif width == 4:    # signed 32-bit
        data = numpy.frombuffer(frames, "<i4").astype(
            numpy.float32) / 2147483648.0
    else:
        raise ValueError("unsupported WAV sample width %d in %s"
                         % (width, path))
    data = data.reshape(-1, n_channels)
    if mono:
        data = data.mean(axis=1)
    return data, rate


class SndFileLoader(FileFilterMixin, FullBatchLoader):
    """Directory-scanning audio loader: labels from subdirectory names,
    one sample per ``window``-frame slice of each track.

    kwargs:
      test_paths/validation_paths/train_paths: directory lists, labels
        from the immediate parent directory (as FileImageLoader);
      included_files/ignored_files: regex filename filters (the shared
        FileFilterMixin contract);
      window: frames per sample (required);
      hop: stride between windows (default = window, non-overlapping);
      mono: mix channels down (default True);
      pad_tail: zero-pad the last partial window instead of dropping it.
    """

    MAPPING = "sndfile_loader"
    EXTENSIONS = (".wav",)

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self._init_filters(kwargs)
        self.window = int(kwargs["window"])
        self.hop = int(kwargs.get("hop", self.window))
        if self.window < 1 or self.hop < 1:
            raise ValueError("window and hop must be >= 1")
        self.mono = bool(kwargs.get("mono", True))
        self.pad_tail = bool(kwargs.get("pad_tail", False))
        self.class_paths = {
            TEST: list(kwargs.get("test_paths", ())),
            VALID: list(kwargs.get("validation_paths", ())),
            TRAIN: list(kwargs.get("train_paths", ())),
        }
        self.sampling_rates = {}

    def get_keys(self, class_index):
        return self.scan_directories(self.class_paths[class_index])

    def get_label(self, key):
        return os.path.basename(os.path.dirname(key))

    def windows_of(self, key):
        """Slice one decoded track into (n, window[, channels]) floats."""
        data, rate = decode_wav(key, mono=self.mono)
        self.sampling_rates[key] = rate
        spans = []
        pos = 0
        while pos + self.window <= len(data):
            spans.append(data[pos:pos + self.window])
            pos += self.hop
        if self.pad_tail and pos < len(data):
            tail = data[pos:]
            pad = [(0, self.window - len(tail))] + \
                [(0, 0)] * (tail.ndim - 1)
            spans.append(numpy.pad(tail, pad))
        return numpy.asarray(spans, numpy.float32)

    def load_data(self):
        samples, labels = [], []
        for cls in (TEST, VALID, TRAIN):
            count = 0
            for key in self.get_keys(cls):
                wins = self.windows_of(key)
                samples.extend(wins)
                labels += [self.get_label(key)] * len(wins)
                count += len(wins)
            self.class_lengths[cls] = count
        if not samples:
            raise ValueError("no WAV files found under the given paths")
        self.original_data.mem = numpy.stack(samples)
        self.original_labels = labels
