"""Data layer: minibatch loaders.

Reference: /root/reference/veles/loader/ (base protocol at base.py:100-120).
"""

from .base import (Loader, LoaderError, TEST, VALID, TRAIN, CLASS_NAME,
                   TRIAGE)                                  # noqa: F401
from .fullbatch import FullBatchLoader, FullBatchLoaderMSE  # noqa: F401
from .image import ImageLoader, FileImageLoader  # noqa: F401
from .pickles import (PicklesLoader, Hdf5Loader,            # noqa: F401
                      FileListLoader)
from .saver import MinibatchesSaver, MinibatchesLoader      # noqa: F401
