"""Data layer: minibatch loaders.

Reference: /root/reference/veles/loader/ (base protocol at base.py:100-120).
"""

from .base import (Loader, LoaderError, TEST, VALID, TRAIN, CLASS_NAME,
                   TRIAGE)                                  # noqa: F401
from .fullbatch import FullBatchLoader, FullBatchLoaderMSE  # noqa: F401
from .image import (ImageLoader, FileImageLoader,           # noqa: F401
                    ImageLoaderMSE, FileImageLoaderMSE)
from .pickles import (PicklesLoader, Hdf5Loader,            # noqa: F401
                      FileListLoader)
from .prefetch import MinibatchPrefetcher, PrefetchError    # noqa: F401
from .shards import ShardedBatchLoader, write_shards        # noqa: F401
from .saver import MinibatchesSaver, MinibatchesLoader      # noqa: F401
from .stream import StreamLoader                            # noqa: F401
from .sound import SndFileLoader                            # noqa: F401
from .interactive import InteractiveLoader                  # noqa: F401
from .restful import RestfulLoader, RestfulResponder        # noqa: F401
from .hdfs import HdfsTextLoader, WebHdfsClient             # noqa: F401
from .lmdb import LMDBFile, LMDBLoader                      # noqa: F401
