"""Export path: trained workflow → portable archive for native inference.

Plays the role of the reference ``Workflow.package_export``
(/root/reference/veles/workflow.py:868-975), which the C++ libVeles runtime
consumes.  Here the archive carries ``contents.json`` (graph + unit
parameters), per-unit weight ``.npy`` files, and optionally a serialized
StableHLO program (``jax.export``) for the compiled inference path.
"""

from .packager import package_export  # noqa: F401
from .model import export_forward, export_model  # noqa: F401
from .loader import PackageLoader  # noqa: F401
