"""StableHLO model export: the compiled-inference half of the package.

The reference's package was consumed by libVeles, which re-implemented
every unit in C++ and replayed the graph
(/root/reference/libVeles/src/workflow_loader.cc, unit_factory.cc:37-65).
The TPU-native design (SURVEY.md §2.10 mapping) replaces that per-unit
C++ zoo with **one serialized StableHLO program**: ``jax.export`` of the
whole forward chain, batch-size polymorphic, plus the weights as ``.npy``
files the loader feeds back in as call arguments.  Any PJRT-capable
runtime (CPU, TPU, the C++ PJRT C API) can then execute the model without
knowing what a "unit" is; XLA owns buffer planning (the
memory_optimizer.cc role).
"""

import json

import numpy


def forward_fn(forwards):
    """The chained eval-mode apply over explicit params (pure)."""
    def fn(params, x):
        h = x
        for i, fwd in enumerate(forwards):
            h = fwd.apply(params[i], h)
        return h
    return fn


def export_forward(workflow, batch="b"):
    """Serialize the workflow's forward chain to StableHLO bytes.

    ``batch``: symbolic dimension name (polymorphic batch — the package
    serves any batch size) or an int for a static-batch artifact.

    Returns (artifact_bytes, metadata_dict)."""
    import jax
    from jax import export as jexport

    forwards = workflow.forwards
    if not forwards:
        raise ValueError("workflow has no forward units to export")
    params = [f.params for f in forwards]
    sample_shape = tuple(int(d)
                         for d in forwards[0].input.shape[1:])
    dtype = numpy.dtype(numpy.float32)
    if isinstance(batch, str):
        dims = jexport.symbolic_shape(
            "%s, %s" % (batch, ", ".join(str(d) for d in sample_shape)))
    else:
        dims = (int(batch),) + sample_shape
    x_struct = jax.ShapeDtypeStruct(dims, dtype)
    params_struct = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(numpy.shape(a), a.dtype), params)
    # trace with every Pallas-capable unit on its pure-XLA path: a
    # Mosaic tpu_custom_call baked into the artifact would break the
    # package's any-backend portability (loader.py, native runtime)
    from ..znicz.nn_units import oracle_only
    with oracle_only():
        exported = jexport.export(jax.jit(forward_fn(forwards)))(
            params_struct, x_struct)
    metadata = {
        "format": "jax.export/stablehlo",
        "input": {"sample_shape": list(sample_shape),
                  "dtype": str(dtype),
                  "batch": batch},
        "forwards": [
            {"unit": f.name, "class": type(f).__name__,
             "params": sorted(f.params),
             "config": f.export_params()
             if hasattr(f, "export_params") else {}}
            for f in forwards],
    }
    return exported.serialize(), metadata


def export_model(workflow, path, precision=32, batch="b"):
    """Full package: arrays + contents.json + model.stablehlo + model.json
    (the complete libVeles-package equivalent)."""
    from .packager import package_export
    artifact, metadata = export_forward(workflow, batch=batch)
    return package_export(
        workflow, path, precision=precision,
        extra_files={"model.stablehlo": artifact,
                     "model.json": json.dumps(metadata, indent=2)})
