"""PackageLoader: read a workflow package back and run inference.

The consumer half of the archive (reference libVeles
workflow_loader.cc + numpy_array_loader.cc roles): parses
``contents.json``, loads the ``.npy`` weights (promoting fp16 → fp32 the
way numpy_array_loader.cc does), deserializes ``model.stablehlo`` and
executes it with the weights as arguments.  Works on any JAX backend;
the C++ runner (native/) reads the same layout.
"""

import io
import json
import threading
import zipfile

import numpy


class PackageLoader:
    """Read-side of export.packager/export.model."""

    def __init__(self, path):
        self.path = path
        with zipfile.ZipFile(path) as zf:
            self.contents = json.loads(zf.read("contents.json"))
            names = set(zf.namelist())
            self.arrays = {}
            for unit in self.contents["units"]:
                for attr, meta in unit.get("arrays", {}).items():
                    arr = numpy.load(io.BytesIO(zf.read(meta["file"])),
                                     allow_pickle=False)
                    if arr.dtype == numpy.float16:
                        arr = arr.astype(numpy.float32)  # fp16 promote
                    self.arrays.setdefault(unit["name"], {})[attr] = arr
            self.model_metadata = (
                json.loads(zf.read("model.json"))
                if "model.json" in names else None)
            self._artifact = (zf.read("model.stablehlo")
                              if "model.stablehlo" in names else None)
        self._exported = None
        self._exported_lock = threading.Lock()

    @property
    def workflow_name(self):
        return self.contents["workflow"]

    @property
    def checksum(self):
        return self.contents.get("checksum")

    def unit_params(self):
        """Params pytree in forward order (what model.stablehlo takes)."""
        if self.model_metadata is None:
            raise ValueError("package has no model.json metadata")
        params = []
        for fwd in self.model_metadata["forwards"]:
            unit_arrays = self.arrays.get(fwd["unit"], {})
            params.append({name: unit_arrays[name]
                           for name in fwd["params"]})
        return params

    def deserialize(self):
        if self._artifact is None:
            raise ValueError("package has no model.stablehlo artifact")
        # double-checked lock: two concurrent FIRST requests must not
        # both deserialize and race the assignment — one pays the
        # deserialization, the loser reuses it
        if self._exported is None:
            with self._exported_lock:
                if self._exported is None:
                    from jax import export as jexport
                    self._exported = jexport.deserialize(self._artifact)
        return self._exported

    def run(self, x):
        """Execute the exported model on a batch (any size when the
        package was exported batch-polymorphic)."""
        import jax.numpy as jnp
        exported = self.deserialize()
        x = jnp.asarray(numpy.asarray(x, numpy.float32))
        return exported.call(self.unit_params(), x)


def main(argv=None):
    """``python -m veles_tpu.export.loader pkg.zip input.npy [out.npy]`` —
    the minimal runner (PJRT plays the libVeles engine role)."""
    import argparse
    p = argparse.ArgumentParser(prog="veles_tpu.export.loader")
    p.add_argument("package")
    p.add_argument("input", help=".npy batch, or 'random' for a smoke run")
    p.add_argument("output", nargs="?", default=None)
    args = p.parse_args(argv)
    loader = PackageLoader(args.package)
    if args.input == "random":
        meta = loader.model_metadata["input"]
        x = numpy.random.RandomState(0).uniform(
            -1, 1, [2] + meta["sample_shape"]).astype(numpy.float32)
    else:
        x = numpy.load(args.input)
    out = numpy.asarray(loader.run(x))
    print("workflow %r: input %s -> output %s" %
          (loader.workflow_name, x.shape, out.shape))
    if args.output:
        numpy.save(args.output, out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
