"""Workflow archive writer.

Produces the portable inference package (reference:
veles/workflow.py:868-975 writes a zip/tgz of ``contents.json`` + fp16/fp32
``.npy`` arrays for libVeles).  Layout here:

- ``contents.json`` — workflow name, checksum, unit list in dependency order
  with class/UUID/links and the names of exported arrays;
- ``<unit>/<attr>.npy`` — each exported array, cast to fp16 or fp32;
- optionally ``model.stablehlo`` — serialized jax.export artifact of the
  compiled forward (added by the model layer when available).

The C++ native runtime (``native/``) and
:class:`veles_tpu.export.loader.PackageLoader` both consume this format.
"""

import json
import os
import tempfile
import zipfile

import numpy


def _exported_arrays(unit):
    out = {}
    for attr in getattr(unit, "exports", ()):
        value = getattr(unit, attr, None)
        if value is None:
            continue
        if hasattr(value, "map_read"):
            if not value:
                continue  # empty Array (e.g. paramless pooling "weights")
            # Array facade: map_read pulls the freshest (possibly
            # device-resident) value — raw ._mem may be stale after
            # device-side training
            arr = numpy.asarray(value.map_read())
        else:
            arr = numpy.asarray(value)
        if arr.dtype == object:
            continue  # not a tensor
        out[attr] = arr
    return out


def package_export(workflow, path, precision=32, extra_files=None):
    """Write the workflow package archive to ``path`` (.zip).

    ``precision`` ∈ {16, 32}: floating arrays are cast to float16/float32
    (the reference's fp16/fp32 export switch).
    """
    if precision not in (16, 32):
        raise ValueError("precision must be 16 or 32")
    fdtype = numpy.float16 if precision == 16 else numpy.float32
    units_desc = []
    arrays = []  # (zip name, ndarray)
    for unit in workflow:
        desc = unit.describe()
        exported = _exported_arrays(unit)
        desc["arrays"] = {}
        for attr, arr in exported.items():
            if numpy.issubdtype(arr.dtype, numpy.floating):
                arr = arr.astype(fdtype)
            # C-order always: consumers (incl. the native npy loader)
            # do not handle fortran_order files
            arr = numpy.ascontiguousarray(arr)
            zname = "%s/%s.npy" % (unit.name.replace("/", "_"), attr)
            desc["arrays"][attr] = {
                "file": zname,
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
            }
            arrays.append((zname, arr))
        params = getattr(unit, "export_params", None)
        if callable(params):
            desc["params"] = params()
        units_desc.append(desc)
    contents = {
        "workflow": workflow.name,
        "checksum": workflow.checksum,
        "precision": precision,
        "units": units_desc,
    }
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("contents.json", json.dumps(contents, indent=2,
                                                default=str))
        for zname, arr in arrays:
            with tempfile.NamedTemporaryFile(suffix=".npy",
                                             delete=False) as tmp:
                numpy.save(tmp, arr)
                tmpname = tmp.name
            try:
                zf.write(tmpname, zname)
            finally:
                os.unlink(tmpname)
        for zname, data in (extra_files or {}).items():
            zf.writestr(zname, data)
    return path
