"""ctypes binding to the native C++ inference engine (native/).

pybind11 is not part of this image, so the binding surface is a flat C
ABI (native/src/capi.cc) loaded via ctypes — the same role the
reference's JNI surface played for libVeles.  Build first::

    cmake -S native -B native/build -G Ninja && cmake --build native/build
"""

import ctypes
import os

import numpy

_LIB_CANDIDATES = (
    # explicit override first: a pip-installed package (site-packages)
    # has no source tree to search relative to — deploy/Dockerfile sets
    # this to its own build output
    os.environ.get("VELES_NATIVE_LIB"),
    os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))),
        "native", "build", "libveles_native.so"),
)


def _find_library(path=None):
    candidates = (path,) if path else _LIB_CANDIDATES
    for cand in candidates:
        if cand and os.path.exists(cand):
            return cand
    return None


def available(path=None):
    return _find_library(path) is not None


class NativeWorkflow:
    """A package loaded into the native engine."""

    def __init__(self, package_path, library_path=None):
        lib_path = _find_library(library_path)
        if lib_path is None:
            raise FileNotFoundError(
                "libveles_native.so not built (cmake -S native -B "
                "native/build && cmake --build native/build)")
        lib = ctypes.CDLL(lib_path)
        lib.veles_load.restype = ctypes.c_void_p
        lib.veles_load.argtypes = [ctypes.c_char_p]
        lib.veles_run.restype = ctypes.c_long
        lib.veles_run.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
            ctypes.c_long, ctypes.POINTER(ctypes.c_long), ctypes.c_long,
            ctypes.POINTER(ctypes.c_float), ctypes.c_long,
            ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_long)]
        lib.veles_last_error.restype = ctypes.c_char_p
        lib.veles_workflow_name.restype = ctypes.c_char_p
        lib.veles_workflow_name.argtypes = [ctypes.c_void_p]
        lib.veles_free.argtypes = [ctypes.c_void_p]
        self._lib = lib
        self._handle = lib.veles_load(package_path.encode())
        if not self._handle:
            raise RuntimeError("native load failed: %s" %
                               lib.veles_last_error().decode())

    @property
    def name(self):
        return self._lib.veles_workflow_name(self._handle).decode()

    def run(self, x, out_capacity=None):
        """Forward the [batch, ...sample] float32 batch natively."""
        x = numpy.ascontiguousarray(x, numpy.float32)
        batch = x.shape[0]
        sample_shape = (ctypes.c_long * (x.ndim - 1))(*x.shape[1:])
        if out_capacity is None:
            out_capacity = max(4 * x.size, 1 << 20)
        out = numpy.empty(out_capacity, numpy.float32)
        out_shape = (ctypes.c_long * 8)()
        out_rank = ctypes.c_long()
        n = self._lib.veles_run(
            self._handle,
            x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            batch, sample_shape, x.ndim - 1,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            out_capacity, out_shape, ctypes.byref(out_rank))
        if n < 0:
            raise RuntimeError("native run failed: %s" %
                               self._lib.veles_last_error().decode())
        shape = tuple(out_shape[i] for i in range(out_rank.value))
        return out[:n].reshape(shape).copy()

    def close(self):
        if self._handle:
            self._lib.veles_free(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
