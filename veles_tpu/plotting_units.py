"""Plotting units: serialize training curves/matrices/images per epoch.

TPU-native re-design of /root/reference/veles/plotting_units.py +
graphics_server.py: the reference pickled whole Plotter objects onto a
ZMQ pub socket for a separate matplotlib process to render
(graphics_server.py:65-113, graphics_client.py:84-380).  Here each
plotter **serializes its data** — one JSONL record per update into the
plots directory — and can optionally render a PNG directly (matplotlib
is in-process; there is no GIL-bound GPU queue to protect, so the
separate-renderer-process architecture is dead weight on TPU).

Units: AccumulatingPlotter (scalar series), MatrixPlotter (confusion
matrix), Histogram (value distribution), ImagePlotter (sample grids —
reference image plotters).  All run at epoch end via ``gate_skip``
wiring done in ``link_decision``/``link_loader``.
"""

import json
import os
import time

import numpy

from .config import root
from .units import Unit


class Plotter(Unit):
    """Base: appends one JSONL record per update; optional PNG render."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.view_group = "PLOTTER"
        self.runs_after_stop = True  # final epoch must still be plotted
        self.plot_name = kwargs.get("name", type(self).__name__)
        self.directory = kwargs.get("directory") or \
            root.common.dirs.get("plots", ".")
        self.render = bool(kwargs.get("render", False))
        self.last_minibatch = None   # linked; plot once per epoch/class
        self.epoch_ended = None
        self._records = 0

    def link_loader(self, loader):
        """Run only when an epoch completes (gate_skip on other runs)."""
        self.link_attrs(loader, "epoch_ended", "last_minibatch")
        self.gate_skip = ~loader.epoch_ended
        return self

    @property
    def path(self):
        return os.path.join(self.directory, self.plot_name + ".jsonl")

    def emit(self, payload):
        os.makedirs(self.directory, exist_ok=True)
        payload = {"plot": self.plot_name, "t": round(time.time(), 3),
                   **payload}
        with open(self.path, "a") as f:
            f.write(json.dumps(payload) + "\n")
        self._records += 1
        if self.render:
            try:
                self.render_png()
            except Exception:
                pass  # rendering is best-effort; data is already on disk

    def render_png(self):
        pass


class AccumulatingPlotter(Plotter):
    """Scalar-vs-epoch series (reference AccumulatingPlotter): watches a
    linked ``input`` attribute, one point per run."""

    MAPPING = "accumulating_plotter"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.input = None            # linked: any scalar-ish attribute
        self.input_field = kwargs.get("input_field")
        self.series = []

    def run(self):
        value = self.input
        if self.input_field is not None:
            value = value[self.input_field] if isinstance(value, (list,
                                                                  dict)) \
                else getattr(value, self.input_field)
        value = float(value)
        self.series.append(value)
        self.emit({"epoch": len(self.series) - 1, "value": value})

    def render_png(self):
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        fig, ax = plt.subplots()
        ax.plot(self.series)
        ax.set_xlabel("epoch")
        ax.set_ylabel(self.plot_name)
        fig.savefig(os.path.join(self.directory, self.plot_name + ".png"))
        plt.close(fig)


class MatrixPlotter(Plotter):
    """Confusion-matrix snapshots (reference MatrixPlotter)."""

    MAPPING = "matrix_plotter"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.input = None            # linked: confusion_matrix Array

    def run(self):
        m = self.input
        m = numpy.asarray(m.map_read() if hasattr(m, "map_read") else m)
        self.emit({"shape": list(m.shape), "matrix": m.tolist()})

    def render_png(self):
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        m = self.input
        m = numpy.asarray(m.map_read() if hasattr(m, "map_read") else m)
        fig, ax = plt.subplots()
        ax.imshow(m, cmap="viridis")
        ax.set_xlabel("true")
        ax.set_ylabel("predicted")
        fig.savefig(os.path.join(self.directory, self.plot_name + ".png"))
        plt.close(fig)


class Histogram(Plotter):
    """Value-distribution histogram (reference Histogram /
    MultiHistogram), e.g. of a weights Array."""

    MAPPING = "histogram_plotter"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.input = None
        self.n_bins = int(kwargs.get("n_bins", 50))

    def run(self):
        v = self.input
        v = numpy.asarray(v.map_read() if hasattr(v, "map_read") else v)
        counts, edges = numpy.histogram(v.ravel(), bins=self.n_bins)
        self.emit({"counts": counts.tolist(), "edges": edges.tolist()})


class ImagePlotter(Plotter):
    """Sample-image grids (reference ImagePlotter/plotting image units):
    saves the first ``count`` samples of the linked Array as PNG."""

    MAPPING = "image_plotter"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.input = None
        self.count = int(kwargs.get("count", 16))
        self.sample_shape = kwargs.get("sample_shape")  # e.g. (28, 28)

    def run(self):
        v = self.input
        v = numpy.asarray(v.map_read() if hasattr(v, "map_read") else v)
        v = v[:self.count]
        if self.sample_shape is not None:
            v = v.reshape((len(v),) + tuple(self.sample_shape))
        path = os.path.join(self.directory, self.plot_name + ".png")
        os.makedirs(self.directory, exist_ok=True)
        self._save_grid(v, path)
        self.emit({"png": path, "count": int(len(v))})

    @staticmethod
    def _save_grid(images, path, cols=4):
        from PIL import Image
        images = numpy.asarray(images, numpy.float64)
        lo, hi = images.min(), images.max()
        images = ((images - lo) / (hi - lo + 1e-12) * 255).astype("uint8")
        n, h, w = images.shape[0], images.shape[1], images.shape[2]
        rows = (n + cols - 1) // cols
        grid = numpy.zeros((rows * h, cols * w) + images.shape[3:], "uint8")
        for i, img in enumerate(images):
            r, c = divmod(i, cols)
            grid[r * h:(r + 1) * h, c * w:(c + 1) * w] = img
        Image.fromarray(grid).save(path)
