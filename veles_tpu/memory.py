"""Array: host numpy storage paired with an HBM-resident ``jax.Array``.

TPU-native re-design of /root/reference/veles/memory.py (Array :110-511,
Watcher device-memory accounting :56-107).  The reference Array keeps one
host buffer and one OpenCL/CUDA buffer with an explicit
map_read / map_write / map_invalidate / unmap protocol.  JAX arrays are
immutable, so the protocol here tracks *validity epochs* instead of mapping:

- ``map_read``   — make the host copy current (device→host only if stale);
- ``map_write``  — make host current and mark it dirty;
- ``map_invalidate`` — mark host dirty *without* a device pull (host will be
  fully overwritten — reference memory.py:137 fast path);
- ``unmap``      — if host is dirty, push to the device (fresh jax.Array,
  sharded when a sharding is set).

Mutating through ``arr.mem[...]`` between map_write/unmap is exactly the
reference idiom (memory.py:137-141).  Device values are created lazily on
first ``devmem`` access, so graphs build host-side and pay one upload.
"""

import threading

import numpy

from .pickling import Pickleable


class Watcher:
    """Process-wide device-memory accounting (reference memory.py:56-107).

    JAX owns the allocator, so this tracks bytes of live Array devmems plus
    the platform's own ``memory_stats`` when available.
    """

    _lock = threading.RLock()  # reentrant: Array.__del__ may fire mid-GC
    #                            inside add/remove on the same thread
    bytes_in_use = 0
    peak_bytes = 0

    @classmethod
    def add(cls, nbytes):
        with cls._lock:
            cls.bytes_in_use += nbytes
            cls.peak_bytes = max(cls.peak_bytes, cls.bytes_in_use)

    @classmethod
    def remove(cls, nbytes):
        with cls._lock:
            cls.bytes_in_use -= nbytes

    @classmethod
    def reset(cls):
        with cls._lock:
            cls.bytes_in_use = 0
            cls.peak_bytes = 0


class Array(Pickleable):
    """Host numpy array + device ``jax.Array`` with validity tracking."""

    def __init__(self, data=None, shallow_pickle=False):
        super().__init__()
        self._mem = None
        self.shallow_pickle = shallow_pickle
        if data is not None:
            self.mem = data

    def init_unpickled(self):
        super().init_unpickled()
        self._devmem_ = None
        self._host_dirty_ = True
        self._device_dirty_ = False
        self._sharding_ = None
        self._accounted_ = 0

    # -- host side -----------------------------------------------------------
    @property
    def mem(self):
        return self._mem

    @mem.setter
    def mem(self, value):
        if value is None:
            self.reset()
            return
        self._mem = numpy.asarray(value)
        self._host_dirty_ = True
        self._device_dirty_ = False

    def reset(self, new_mem=None):
        """Drop both copies (reference memory.py:331)."""
        self._release_devmem()
        self._mem = new_mem
        self._host_dirty_ = new_mem is not None
        self._device_dirty_ = False

    def __bool__(self):
        return self._mem is not None or self._devmem_ is not None

    @property
    def shape(self):
        m = self._mem if self._mem is not None else self._devmem_
        return m.shape if m is not None else ()

    @property
    def dtype(self):
        m = self._mem if self._mem is not None else self._devmem_
        return m.dtype if m is not None else None

    @property
    def size(self):
        m = self._mem if self._mem is not None else self._devmem_
        return m.size if m is not None else 0

    @property
    def nbytes(self):
        m = self._mem if self._mem is not None else self._devmem_
        return m.nbytes if m is not None else 0

    @property
    def sample_size(self):
        """Elements per leading-axis sample (reference memory.py)."""
        if not self.shape:
            return 0
        return self.size // self.shape[0]

    def __len__(self):
        return self.shape[0] if self.shape else 0

    def __getitem__(self, idx):
        self.map_read()
        return self._mem[idx]

    def __setitem__(self, idx, value):
        self.map_write()
        self._mem[idx] = value

    # -- map/unmap protocol --------------------------------------------------
    def map_read(self):
        if self._device_dirty_ and self._devmem_ is not None:
            self._mem = numpy.asarray(self._devmem_)
            self._device_dirty_ = False
        return self._mem

    def map_write(self):
        self.map_read()
        self._host_dirty_ = True
        return self._mem

    def map_invalidate(self):
        if self._mem is None and self._devmem_ is not None:
            # need a host buffer of the right shape, contents irrelevant
            self._mem = numpy.empty(self._devmem_.shape,
                                    self._devmem_.dtype)
        self._host_dirty_ = True
        self._device_dirty_ = False
        return self._mem

    def unmap(self):
        if self._host_dirty_ and self._mem is not None:
            self._upload()
        return self

    # -- device side ---------------------------------------------------------
    @property
    def devmem(self):
        """The device-resident jax.Array (uploads lazily if host is newer)."""
        if self._host_dirty_ or self._devmem_ is None:
            if self._mem is None:
                return None
            self._upload()
        return self._devmem_

    @devmem.setter
    def devmem(self, value):
        """Accept a fresh device value (the output of a jitted step); the
        host copy becomes stale until map_read."""
        self._release_devmem()
        self._devmem_ = value
        if value is not None:
            self._account(value)
            self._device_dirty_ = True
            self._host_dirty_ = False

    def swap_devmem(self, value):
        """Hot-path twin of the ``devmem`` setter (the graph compiler
        writes every traced output back each step): one combined
        accounting update under a single Watcher lock instead of
        release+add."""
        try:
            nbytes = value.nbytes
        except Exception:  # noqa: BLE001
            nbytes = 0
        with Watcher._lock:
            Watcher.bytes_in_use += nbytes - self._accounted_
            if Watcher.bytes_in_use > Watcher.peak_bytes:
                Watcher.peak_bytes = Watcher.bytes_in_use
        self._accounted_ = nbytes
        self._devmem_ = value
        self._device_dirty_ = True
        self._host_dirty_ = False

    def set_sharding(self, sharding):
        """Future uploads place the value with this jax.sharding.Sharding."""
        self._sharding_ = sharding
        if self._devmem_ is not None:
            # re-place on next access
            self.map_read()
            self._release_devmem()
            self._host_dirty_ = True

    def _upload(self):
        import jax
        self._release_devmem()
        if self._sharding_ is not None:
            self._devmem_ = jax.device_put(self._mem, self._sharding_)
        else:
            self._devmem_ = jax.device_put(self._mem)
        self._account(self._devmem_)
        self._host_dirty_ = False
        self._device_dirty_ = False

    def _account(self, value):
        try:
            nbytes = value.nbytes
        except Exception:
            nbytes = 0
        self._accounted_ = nbytes
        Watcher.add(nbytes)

    def _release_devmem(self):
        if self._devmem_ is not None:
            Watcher.remove(self._accounted_)
            self._accounted_ = 0
            self._devmem_ = None

    def __del__(self):
        try:
            self._release_devmem()
        except Exception:
            pass  # interpreter teardown

    # -- pickling ------------------------------------------------------------
    def __getstate__(self):
        """Device values are pulled to host before pickling (reference
        memory.py:284-299); shallow_pickle drops the payload for huge
        datasets.  Inside a sharded-checkpoint extraction context
        (checkpoint/tensors.py) large payloads are diverted into the
        sink instead: a device-current value is handed over zero-copy
        as its immutable jax.Array — no device→host pull on the capture
        thread — and a host-current value is snapshotted once."""
        from .checkpoint.tensors import TensorStub, active_sink
        sink = active_sink()
        if sink is not None and not self.shallow_pickle:
            if self._device_dirty_ and self._devmem_ is not None:
                payload, needs_copy = self._devmem_, False
            else:
                payload, needs_copy = self._mem, True
            nbytes = getattr(payload, "nbytes", None)  # None: already a stub
            if nbytes is not None and nbytes >= sink.min_bytes:
                state = super().__getstate__()
                state["_mem"] = TensorStub(
                    sink.add(payload, copy=needs_copy))
                return state
        self.map_read()
        state = super().__getstate__()
        if self.shallow_pickle:
            state["_mem"] = None
        return state

    def __repr__(self):
        return "<Array %s %s host_dirty=%s device=%s>" % (
            self.shape, self.dtype, self._host_dirty_,
            self._devmem_ is not None)
