"""Content-addressed on-disk executable store.

Durability follows the snapshotter's conventions (snapshotter.py, PR 4):
every write is ``*.tmp`` + flush + fsync + atomic ``os.rename`` — a
kill at any point leaves either no entry or a complete one, never a
truncated file at its final name.  Reads that fail (or entries the
caller finds undeserializable) are *quarantined*: renamed aside with a
``.corrupt`` suffix so the next lookup is a clean miss and the evidence
survives for inspection — a bad cache entry must never crash a start or
poison a second one.

Eviction is a size-budget LRU sweep: entry mtimes are touched on every
hit, and when the store exceeds ``max_bytes`` the oldest entries go
first.  Concurrent processes are safe by construction: writes are
atomic renames (last writer wins, both wrote the same content for the
same key) and eviction tolerates entries vanishing underneath it.
"""

import logging
import os

log = logging.getLogger("veles_tpu.compilecache")

#: cache entry suffix; quarantined entries get SUFFIX + ".corrupt"
SUFFIX = ".jexe"


class ExecutableStore:
    """key (hex string) -> bytes blobs under one directory."""

    def __init__(self, directory, max_bytes=None):
        self.directory = os.path.abspath(directory)
        self.max_bytes = int(max_bytes) if max_bytes else None
        os.makedirs(self.directory, exist_ok=True)

    def path_for(self, key):
        return os.path.join(self.directory, key + SUFFIX)

    # -- read ----------------------------------------------------------------
    def get(self, key):
        """The stored blob, or None (miss).  A hit refreshes the entry's
        mtime — the LRU clock."""
        path = self.path_for(key)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            return None
        try:
            os.utime(path, None)
        except OSError:
            pass                    # concurrently evicted: still a hit
        return blob

    # -- write ---------------------------------------------------------------
    def put(self, key, blob):
        """Atomically persist ``blob`` under ``key``; then sweep the
        size budget.  Returns the bytes written."""
        path = self.path_for(key)
        tmp = path + ".tmp.%d" % os.getpid()
        try:
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.rename(tmp, path)
        except OSError:
            # a full/read-only cache disk must never fail the caller —
            # the compile already succeeded; the entry is just not saved
            log.warning("compile cache: could not persist entry %s under "
                        "%s", key[:16], self.directory, exc_info=True)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return 0
        self.evict()
        return len(blob)

    def quarantine(self, key, reason=""):
        """Rename a bad entry aside (``.corrupt``) so the next lookup is
        a clean miss; the caller recompiles.  Idempotent."""
        path = self.path_for(key)
        try:
            os.replace(path, path + ".corrupt")
        except OSError:
            return False
        # debug, not warning: the cache layer owns the single user-
        # visible "corrupt entry" warning per key (log-once contract)
        log.debug("compile cache: quarantined entry %s (%s) -> "
                  "%s.corrupt", key[:16], reason or "undeserializable",
                  os.path.basename(path))
        return True

    # -- accounting / eviction -----------------------------------------------
    def entries(self):
        """[(key, size, mtime)] for every live entry (no .corrupt/.tmp)."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            if not name.endswith(SUFFIX):
                continue
            path = os.path.join(self.directory, name)
            try:
                st = os.stat(path)
            except OSError:
                continue            # raced with eviction elsewhere
            out.append((name[:-len(SUFFIX)], st.st_size, st.st_mtime))
        return out

    def total_bytes(self):
        return sum(size for _, size, _ in self.entries())

    def evict(self):
        """Drop oldest-used entries until the store fits ``max_bytes``.
        Returns the number of entries removed."""
        if not self.max_bytes:
            return 0
        entries = self.entries()
        total = sum(size for _, size, _ in entries)
        removed = 0
        for key, size, _ in sorted(entries, key=lambda e: e[2]):
            if total <= self.max_bytes:
                break
            try:
                os.unlink(self.path_for(key))
            except OSError:
                continue
            total -= size
            removed += 1
        if removed:
            log.info("compile cache: evicted %d entr%s (budget %d bytes)",
                     removed, "y" if removed == 1 else "ies",
                     self.max_bytes)
        return removed
