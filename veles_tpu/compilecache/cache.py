"""get_or_compile: the jit -> lower -> compile wrap with persistence.

:class:`CompileCache` sits between a ``jax.jit`` function and XLA: the
lowering is fingerprinted (:mod:`.keys`), looked up in the on-disk
store (:mod:`.store`), and either **deserialized** back into a loaded
executable (``jax.experimental.serialize_executable`` — milliseconds)
or **compiled** fresh and persisted for the next process.  Every
outcome is observable: ``veles_compile_cache_{hits,misses,bytes,
seconds_saved}_total`` in the process-global MetricsRegistry and
``compile.cache_hit`` / ``compile.miss`` trace spans.

Failure policy — the cache may only ever cost a recompile, never a
crash or a wrong result: a truncated/undeserializable entry is
quarantined (renamed aside) and the caller falls back to a fresh
compile; a full disk loses the *persist*, not the compile; any
environment drift (jax/jaxlib version, platform, device kind) changes
the key and misses cleanly.

:class:`AotStep` is the training-side adapter: a first-call AOT wrapper
around a jitted step function that lowers against the concrete call's
shapes, runs ``get_or_compile``, and executes the loaded executable
thereafter — with a one-way fallback to the plain jit path on ANY
surprise, so enabling the cache can never change training results.
"""

import logging
import os
import pickle
import time

from ..config import root
from ..logger import events
from ..observability.registry import REGISTRY
from .keys import cache_key
from .manifest import WarmupManifest
from .store import ExecutableStore

log = logging.getLogger("veles_tpu.compilecache")

#: env var a supervisor (ElasticRunner) uses to hand the cache dir to
#: respawned children that don't re-read its programmatic config
CACHE_DIR_ENV = "VELES_COMPILE_CACHE_DIR"
MAX_BYTES_ENV = "VELES_COMPILE_CACHE_MAX_BYTES"

#: store blob format version — bump on layout change (old entries then
#: quarantine-and-recompile once, which is the upgrade path)
_FORMAT = 1


class CompileCache:
    """Persistent executable cache over one directory."""

    def __init__(self, directory, max_bytes=None, registry=None):
        registry = registry or REGISTRY
        self.store = ExecutableStore(directory, max_bytes=max_bytes)
        self.manifest = WarmupManifest(
            os.path.join(self.store.directory, "warmup_manifest.json"))
        self._c_hits = registry.counter(
            "veles_compile_cache_hits_total",
            "Executable cache hits (deserialize instead of compile)")
        self._c_misses = registry.counter(
            "veles_compile_cache_misses_total",
            "Executable cache misses (fresh XLA compile)")
        self._c_bytes = registry.counter(
            "veles_compile_cache_bytes_total",
            "Bytes read from + written to the executable store")
        self._c_saved = registry.counter(
            "veles_compile_cache_seconds_saved_total",
            "Recorded compile seconds avoided by cache hits, net of "
            "deserialization time")
        self._quarantined = set()   # keys warned about (log once)

    # -- the core ------------------------------------------------------------
    def get_or_compile(self, jitted, *arg_structs, name="jit",
                       key_extra=None):
        """Lower ``jitted`` at ``arg_structs`` and return
        ``(loaded_or_compiled, cache_hit)``.

        ``cache_hit`` is True when the executable came off disk, False
        when XLA compiled it fresh (and the entry was persisted).
        """
        lowered = jitted.lower(*arg_structs)
        return self.load_or_compile(lowered, name=name,
                                    key_extra=key_extra)

    def load_or_compile(self, lowered, name="jit", key_extra=None):
        """Same contract as :meth:`get_or_compile`, from a Lowered."""
        key = cache_key(lowered, extra=key_extra)
        loaded = self._try_load(key, name)
        if loaded is not None:
            return loaded, True
        t0 = time.perf_counter()
        compiled = lowered.compile()
        dt = time.perf_counter() - t0
        self._c_misses.inc()
        events.span("compile.miss", dt, fn=name, key=key[:16])
        self._persist(key, compiled, dt, name)
        return compiled, False

    def _try_load(self, key, name):
        blob = self.store.get(key)
        if blob is None:
            return None
        t0 = time.perf_counter()
        try:
            entry = pickle.loads(blob)
            if entry["format"] != _FORMAT or entry["key"] != key:
                raise ValueError("entry format/key mismatch")
            from jax.experimental import serialize_executable
            loaded = serialize_executable.deserialize_and_load(
                *entry["exe"])
        except Exception as exc:  # noqa: BLE001 — ANY bad entry: miss
            self.store.quarantine(key, reason=str(exc)[:120])
            if key not in self._quarantined:
                self._quarantined.add(key)
                log.warning("compile cache: entry %s for %r was corrupt "
                            "(%s: %s); recompiling", key[:16], name,
                            type(exc).__name__, str(exc)[:200])
            return None
        dt = time.perf_counter() - t0
        self._c_hits.inc()
        self._c_bytes.inc(len(blob))
        self._c_saved.inc(max(0.0,
                              float(entry.get("compile_seconds", 0.0))
                              - dt))
        events.span("compile.cache_hit", dt, fn=name, key=key[:16],
                    bytes=len(blob))
        return loaded

    def _persist(self, key, compiled, compile_seconds, name):
        try:
            from jax.experimental import serialize_executable
            exe = serialize_executable.serialize(compiled)
            blob = pickle.dumps({"format": _FORMAT, "key": key,
                                 "name": str(name),
                                 "compile_seconds":
                                     round(float(compile_seconds), 4),
                                 "exe": exe},
                                protocol=pickle.HIGHEST_PROTOCOL)
        except Exception as exc:  # noqa: BLE001 — unserializable
            # executable (backend without serialization support): the
            # compile still succeeded, this process just stays warm-only
            log.info("compile cache: executable for %r not serializable "
                     "(%s: %s); not persisted", name,
                     type(exc).__name__, str(exc)[:200])
            return
        self._c_bytes.inc(self.store.put(key, blob))

    # -- stats ---------------------------------------------------------------
    def stats(self):
        return {"directory": self.store.directory,
                "entries": len(self.store.entries()),
                "total_bytes": self.store.total_bytes(),
                "max_bytes": self.store.max_bytes,
                "hits": int(self._c_hits.value),
                "misses": int(self._c_misses.value)}


# -- config resolution --------------------------------------------------------

def resolve_config():
    """(directory_or_None, max_bytes) from
    ``root.common.compile_cache.{enabled, dir, max_bytes}`` with the
    :data:`CACHE_DIR_ENV` / :data:`MAX_BYTES_ENV` env fallbacks.  A
    None directory means the cache is OFF — exact pre-cache behavior."""
    cfg = root.common.compile_cache
    if not cfg.get("enabled", True):
        return None, None
    directory = cfg.get("dir", None) or os.environ.get(CACHE_DIR_ENV)
    max_bytes = cfg.get("max_bytes", None)
    if max_bytes is None and os.environ.get(MAX_BYTES_ENV):
        try:
            max_bytes = int(os.environ[MAX_BYTES_ENV])
        except ValueError:
            max_bytes = None
    return (str(directory) if directory else None), max_bytes


_instances = {}


def default_cache():
    """The process-wide :class:`CompileCache` for the configured dir,
    or None when no dir is configured (cache off)."""
    directory, max_bytes = resolve_config()
    if not directory:
        return None
    key = (os.path.abspath(directory), max_bytes)
    cache = _instances.get(key)
    if cache is None:
        cache = _instances[key] = CompileCache(directory,
                                               max_bytes=max_bytes)
    return cache


def reset_default_caches():
    """Drop memoized instances (tests that switch config dirs)."""
    _instances.clear()


def inject_env(env=None):
    """Return ``env`` (default: a copy of os.environ) with the
    configured cache dir exported for a child process — how
    ElasticRunner respawns inherit the cache without re-reading the
    supervisor's programmatic config.  Also forwards the engine-level
    JAX persistent compilation cache dir when set."""
    directory, max_bytes = resolve_config()
    jax_cc = root.common.engine.get("compilation_cache_dir", None)
    # the tuning store rides the same respawn plumbing: children
    # resolve the SAME winners, so a respawn recompiles nothing new
    # (literal env name — importing veles_tpu.autotune here would cycle)
    tune_dir = root.common.get("autotune", {}).get("dir", None)
    if not directory and not jax_cc and not tune_dir:
        return env
    env = dict(os.environ if env is None else env)
    if directory:
        env.setdefault(CACHE_DIR_ENV, os.path.abspath(directory))
        if max_bytes:
            env.setdefault(MAX_BYTES_ENV, str(int(max_bytes)))
    if jax_cc:
        # jax config options read their env default at import time in
        # the child — the one-knob satellite rides along
        env.setdefault("JAX_COMPILATION_CACHE_DIR",
                       os.path.abspath(str(jax_cc)))
    if tune_dir:
        env.setdefault("VELES_AUTOTUNE_DIR",
                       os.path.abspath(str(tune_dir)))
    return env


# -- the training-side adapter ------------------------------------------------

class AotStep:
    """First-call AOT wrapper around a jitted step function.

    The fused train step's shapes are only known at the first call (the
    loader owns them), so the wrapper lowers THERE: arg shapes/dtypes
    become ``ShapeDtypeStruct``s (python int/float scalars pinned to
    int32/float32, matching what the jit trace would produce), the
    executable comes from :meth:`CompileCache.get_or_compile`, and
    every later call runs it directly.

    Safety: on ANY failure — lowering, cache, or executing the loaded
    executable — the wrapper permanently falls back to the wrapped
    ``jax.jit`` function (logged once).  Enabling the cache can slow a
    step down to exactly the old path, never change its result.

    Interface parity with ``jax.jit`` functions where the codebase
    relies on it: ``__wrapped__`` (scan/mesh steps re-jit from the raw
    function) and ``_cache_size`` (the StepProfiler's recompile
    accounting — stays 0 while the AOT path serves every call).
    """

    def __init__(self, jitted, cache, name, key_extra=None):
        self._jitted = jitted
        self._cache = cache
        self._name = name
        self._key_extra = key_extra
        self._compiled = None
        self._fallback = False
        self.cache_hit = None       # None until the first call decides
        wrapped = getattr(jitted, "__wrapped__", None)
        if wrapped is not None:
            self.__wrapped__ = wrapped

    def _cache_size(self):
        fn = getattr(self._jitted, "_cache_size", None)
        try:
            return int(fn()) if callable(fn) else 0
        except Exception:  # noqa: BLE001 — diagnostics never raise
            return 0

    # scalar pinning: a python int/float traces as a weak 32-bit scalar
    # under the default x64-off config; the AOT struct pins the same
    # width strongly and the call-side twin converts to match
    @staticmethod
    def _leaf_struct(a):
        import jax
        import numpy
        if isinstance(a, (bool, numpy.bool_)):
            return jax.ShapeDtypeStruct((), numpy.bool_)
        if isinstance(a, (int, numpy.integer)):
            return jax.ShapeDtypeStruct((), numpy.int32)
        if isinstance(a, (float, numpy.floating)):
            return jax.ShapeDtypeStruct((), numpy.float32)
        return jax.ShapeDtypeStruct(numpy.shape(a), a.dtype)

    @staticmethod
    def _leaf_harden(a):
        import numpy
        if isinstance(a, (bool, numpy.bool_)):
            return numpy.bool_(a)
        if isinstance(a, (int, numpy.integer)):
            return numpy.int32(a)
        if isinstance(a, (float, numpy.floating)):
            return numpy.float32(a)
        return a

    def _ensure_compiled(self, args):
        import jax
        structs = jax.tree_util.tree_map(self._leaf_struct, args)
        self._compiled, self.cache_hit = self._cache.get_or_compile(
            self._jitted, *structs, name=self._name,
            key_extra=self._key_extra)

    def __call__(self, *args):
        if not self._fallback:
            try:
                if self._compiled is None:
                    self._ensure_compiled(args)
                import jax
                return self._compiled(
                    *jax.tree_util.tree_map(self._leaf_harden, args))
            except Exception as exc:  # noqa: BLE001 — never change
                # results: hand the call to the plain jit path for good
                self._fallback = True
                self._compiled = None
                log.warning("compile cache: AOT path for %r disabled "
                            "(%s: %s); falling back to jax.jit",
                            self._name, type(exc).__name__,
                            str(exc)[:200])
        return self._jitted(*args)
