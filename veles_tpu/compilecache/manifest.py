"""Warmup manifests: what a restart should precompile, and first.

Serving records every (model, bucket) it actually compiled; on the next
start the scheduler warms those entries FIRST (through the executable
cache — all hits on a warm cache), so the shapes real traffic uses are
ready before the speculative tail of the bucket ladder.  The manifest
is advisory: a missing/corrupt file means "no history", never an error.

The JSON file lives next to the cache entries (atomic tmp+fsync+rename
writes, same conventions as :mod:`.store`) and is tiny — one record per
(model, bucket) ever compiled.
"""

import json
import logging
import os
import threading

log = logging.getLogger("veles_tpu.compilecache")


class WarmupManifest:
    """Thread-safe (model, bucket) history backed by one JSON file."""

    def __init__(self, path):
        self.path = os.path.abspath(path)
        self._lock = threading.Lock()
        self._models, self._configs = self._load()

    def _load(self):
        try:
            with open(self.path) as f:
                data = json.load(f)
            models = data.get("models", {})
            if not isinstance(models, dict):
                raise ValueError("manifest 'models' is not a dict")
            configs = data.get("configs", {})
            if not isinstance(configs, dict):
                raise ValueError("manifest 'configs' is not a dict")
            return ({str(name): list(entries)
                     for name, entries in models.items()},
                    {str(name): dict(sites)
                     for name, sites in configs.items()})
        except FileNotFoundError:
            return {}, {}
        except (OSError, ValueError) as exc:
            # a mangled manifest only loses warmup ORDER, never
            # correctness — start empty and say so once
            log.warning("warmup manifest %s unreadable (%s); starting "
                        "empty", self.path, exc)
            return {}, {}

    def _save_locked(self):
        tmp = self.path + ".tmp.%d" % os.getpid()
        try:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            doc = {"models": self._models}
            if self._configs:       # old readers only look at "models"
                doc["configs"] = self._configs
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            os.rename(tmp, self.path)
        except OSError:
            log.warning("warmup manifest: could not persist %s",
                        self.path, exc_info=True)
            try:
                os.unlink(tmp)
            except OSError:
                pass

    # -- recording -----------------------------------------------------------
    def record(self, model, bucket, sample_shape=None):
        """Note that ``model`` compiled ``bucket``; persists immediately
        (compiles are rare).  Returns True when the entry is new."""
        entry = {"bucket": int(bucket)}
        if sample_shape is not None:
            entry["sample_shape"] = [int(d) for d in sample_shape]
        with self._lock:
            entries = self._models.setdefault(str(model), [])
            if any(e.get("bucket") == entry["bucket"] for e in entries):
                return False
            entries.append(entry)
            entries.sort(key=lambda e: e.get("bucket", 0))
            self._save_locked()
        return True

    def record_config(self, model, site, config):
        """Note the tuned config ``model`` resolved for autotune
        ``site`` (e.g. ``serving.bucket_ladder``) — advisory, like
        buckets: a warm restart reads the same geometry back before
        compiling, so tuned winners never cost a fresh compile.
        Returns True when the stored value changed."""
        config = dict(config)
        with self._lock:
            sites = self._configs.setdefault(str(model), {})
            if sites.get(str(site)) == config:
                return False
            sites[str(site)] = config
            self._save_locked()
        return True

    # -- reading -------------------------------------------------------------
    def buckets(self, model):
        """Recorded bucket sizes for ``model``, smallest first."""
        with self._lock:
            return sorted(int(e["bucket"])
                          for e in self._models.get(str(model), ())
                          if "bucket" in e)

    def configs(self, model):
        """Recorded tuned configs for ``model``: {site: config}."""
        with self._lock:
            return {site: dict(cfg) for site, cfg
                    in self._configs.get(str(model), {}).items()}

    def models(self):
        with self._lock:
            return sorted(set(self._models) | set(self._configs))

    def forget(self, model):
        """Drop one model's history (hot-unload / tests)."""
        with self._lock:
            had = self._models.pop(str(model), None) is not None
            had = (self._configs.pop(str(model), None)
                   is not None) or had
            if not had:
                return False
            self._save_locked()
        return True
