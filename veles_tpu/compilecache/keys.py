"""Cache keys: fingerprint a lowering so stale entries MISS.

A serialized executable is only reusable by a process whose compiler
would have produced the same binary.  The key therefore hashes the
program (StableHLO module text — argument donation and shardings are
part of the text) together with everything that changes codegen out
from under it: jax/jaxlib versions, the backend platform, the device
kind and count.  Any drift produces a *different key* — a clean miss
and a fresh compile — never a deserialization of a wrong or
incompatible executable.
"""

import hashlib


def environment_fingerprint():
    """The compilation environment as a stable string: versions,
    platform, device kind and count.  Split out (and monkeypatchable in
    tests) so version-mismatch behavior is testable without installing
    a second jaxlib."""
    import jax
    import jaxlib
    try:
        devices = jax.devices()
        platform = devices[0].platform
        kind = getattr(devices[0], "device_kind", "?")
        count = len(devices)
    except Exception:  # noqa: BLE001 — no backend: still a valid key
        platform, kind, count = "none", "?", 0
    return "jax=%s;jaxlib=%s;platform=%s;device_kind=%s;devices=%d" % (
        jax.__version__, jaxlib.__version__, platform, kind, count)


def cache_key(lowered, extra=None):
    """SHA-256 key for a ``jax.stages.Lowered`` (hex string).

    ``extra`` is an optional dict of caller-supplied discriminators
    (hashed as sorted repr); the module text itself already covers
    shapes, dtypes, donation and shardings.
    """
    h = hashlib.sha256()
    h.update(lowered.as_text().encode())
    h.update(b"\x00")
    h.update(environment_fingerprint().encode())
    if extra:
        h.update(b"\x00")
        h.update(repr(sorted(extra.items())).encode())
    return h.hexdigest()
