"""Persistent compiled-executable cache + warmup manifests.

Every process (re)start used to pay full XLA compilation from scratch:
the serving scheduler AOT-compiles ``log2(max_batch)+1`` bucket
executables per model at startup, and the fused training step re-jits
after every :class:`~veles_tpu.distributed.ElasticRunner` respawn or
snapshot restore.  This package makes compiled executables survive the
process (the ahead-of-time-compiled serving posture TVM argues for,
PAPERS.md, extended across process lifetimes):

- :mod:`.keys` — fingerprint a lowering into a cache key (StableHLO
  text + jax/jaxlib versions + backend platform + device kind/count +
  caller extras), so a stale entry *misses* instead of misloading;
- :mod:`.store` — content-addressed on-disk store (tmp + fsync +
  atomic rename, the snapshotter's durability conventions), with a
  size-budget LRU sweep and quarantine-on-corrupt;
- :mod:`.cache` — :class:`CompileCache.get_or_compile` wrapping
  ``jit -> lower -> compile`` with
  ``jax.experimental.serialize_executable``, plus :class:`AotStep`,
  the first-call AOT wrapper the fused train step uses;
- :mod:`.manifest` — :class:`WarmupManifest`: serving records every
  (model, bucket) actually compiled; on restart the scheduler
  precompiles from the manifest through the cache.

Config: ``root.common.compile_cache.{dir, enabled, max_bytes,
background_warmup}`` (or ``$VELES_COMPILE_CACHE_DIR``).  Default on
when a dir is set; unset dir = exact pre-cache behavior.
"""

from .cache import (AotStep, CompileCache, default_cache, inject_env,
                    reset_default_caches, resolve_config)
from .keys import cache_key, environment_fingerprint
from .manifest import WarmupManifest
from .store import ExecutableStore

__all__ = ["AotStep", "CompileCache", "ExecutableStore", "WarmupManifest",
           "cache_key", "default_cache", "environment_fingerprint",
           "inject_env", "reset_default_caches", "resolve_config"]
