"""Tiered KV-block store: HBM chains demote to host RAM, then disk.

The HBM pool (``serving/kvcache.py``) already keeps refcount-0 chains
resident in an LRU cache and reclaims them only under allocation
pressure.  This module is what happens *instead of dying* when that
eviction fires: the pool's ``on_evict`` hook hands the block's device
contents (serialized through the session wire, ``sessions.pack_block``)
to a :class:`TieredKVStore`, which parks them in a bounded host-RAM
tier and cascades the host tier's own LRU overflow into a disk tier
backed by the checkpoint chunk store — the same sha256
content-addressing end to end, so a disk chunk IS a publishable KV
block and identical chains written by different sessions (or different
replica incarnations) land on the same bytes.

Lookups touch-promote: a host hit refreshes its LRU slot, a disk hit is
copied back into the host tier (the readmit that follows re-publishes
it into HBM), so a hot chain climbs back up the hierarchy exactly as
far as it is used.  Each tier evicts independently by byte capacity;
the disk tier's key index is one atomically-written file per chain key,
so a SIGKILL at any point leaves a consistent tier that re-advertises
its chains after respawn.
"""

import os

from ..checkpoint.store import ChunkStore, CorruptChunkError

__all__ = ["HostTier", "DiskTier", "TieredKVStore",
           "DIR_ENV", "ADVERT_HEX", "advert_key"]

#: environment variable replicas read for their disk-tier directory
#: (set per replica id by the supervisor so the tier survives respawn)
DIR_ENV = "VELES_KVTIER_DIR"

#: chain keys are truncated to this many hex chars in advertisements,
#: routing headers and inspection dumps — 64 bits of sha256 is plenty
#: to make collisions a non-concern at fleet scale while keeping the
#: /readyz piggyback payload small
ADVERT_HEX = 16

_REF_SUFFIX = ".ref"


def advert_key(key):
    """Advertised (truncated-hex) form of a chain key."""
    if isinstance(key, (bytes, bytearray)):
        key = bytes(key).hex()
    return str(key)[:ADVERT_HEX]


class HostTier:
    """Bounded LRU of chain key -> serialized block bytes in host RAM."""

    name = "host"

    def __init__(self, capacity_bytes):
        self.capacity_bytes = int(capacity_bytes)
        self._blocks = {}        # key -> bytes; dict order IS the LRU
        self.used_bytes = 0

    def __len__(self):
        return len(self._blocks)

    def __contains__(self, key):
        return key in self._blocks

    def keys(self):
        return list(self._blocks)

    def get(self, key):
        data = self._blocks.get(key)
        if data is not None:                      # touch: newest = last
            del self._blocks[key]
            self._blocks[key] = data
        return data

    def put(self, key, data):
        """Insert (or refresh) a block; returns the ``(key, data)``
        pairs LRU-evicted to make room, for the caller to cascade into
        the next tier down."""
        old = self._blocks.pop(key, None)
        if old is not None:
            self.used_bytes -= len(old)
        self._blocks[key] = data
        self.used_bytes += len(data)
        spilled = []
        while self.used_bytes > self.capacity_bytes and len(self._blocks) > 1:
            k = next(iter(self._blocks))          # oldest
            v = self._blocks.pop(k)
            self.used_bytes -= len(v)
            spilled.append((k, v))
        if self.used_bytes > self.capacity_bytes:  # sole block too big
            k, v = self._blocks.popitem()
            self.used_bytes -= len(v)
            spilled.append((k, v))
        return spilled

    def discard(self, key):
        data = self._blocks.pop(key, None)
        if data is not None:
            self.used_bytes -= len(data)

    def check_integrity(self):
        bad = []
        actual = sum(len(v) for v in self._blocks.values())
        if actual != self.used_bytes:
            bad.append("host tier byte accounting %d != actual %d"
                       % (self.used_bytes, actual))
        if self.used_bytes > self.capacity_bytes and len(self._blocks) > 1:
            bad.append("host tier over capacity with evictable blocks")
        return bad


class DiskTier:
    """Chain key -> serialized block bytes, durable across SIGKILL.

    Layout under ``directory``::

        chunks/<sha256-of-bytes>.chunk   content (ChunkStore: atomic
                                         write, verified read, deduped)
        keys/<chain-key-hex>.ref         the chunk digest (atomic rename)

    Payload bytes are canonical (``sessions.pack_block``), so two chains
    with identical contents share one chunk no matter who wrote them.
    The ref file's mtime is the LRU clock: reads touch it, capacity
    eviction drops the stalest refs and then gc's unreferenced chunks.
    """

    name = "disk"

    def __init__(self, directory, capacity_bytes=0):
        self.directory = os.path.abspath(directory)
        self.capacity_bytes = int(capacity_bytes)   # 0 == unbounded
        self._chunks = ChunkStore(os.path.join(self.directory, "chunks"))
        self._keys_dir = os.path.join(self.directory, "keys")
        os.makedirs(self._keys_dir, exist_ok=True)

    def _ref_path(self, key_hex):
        return os.path.join(self._keys_dir, key_hex + _REF_SUFFIX)

    def keys(self):
        """Chain keys (hex) resident on disk — rebuilt by listing the
        index, which is how a respawned replica re-advertises chains
        its previous incarnation demoted."""
        try:
            names = os.listdir(self._keys_dir)
        except OSError:
            return []
        return [n[:-len(_REF_SUFFIX)] for n in names
                if n.endswith(_REF_SUFFIX)]

    def __contains__(self, key_hex):
        return os.path.exists(self._ref_path(key_hex))

    def __len__(self):
        return len(self.keys())

    @property
    def used_bytes(self):
        return self._chunks.total_bytes()

    def put(self, key_hex, data):
        digest, _ = self._chunks.put(data)
        path = self._ref_path(key_hex)
        tmp = path + ".tmp.%d" % os.getpid()
        with open(tmp, "w", encoding="ascii") as f:
            f.write(digest)
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, path)
        if self.capacity_bytes:
            self._enforce_capacity(keep=key_hex)

    def get(self, key_hex):
        path = self._ref_path(key_hex)
        try:
            with open(path, "r", encoding="ascii") as f:
                digest = f.read().strip()
        except OSError:
            return None
        try:
            data = self._chunks.get(digest)
        except (OSError, CorruptChunkError):
            # dangling or corrupt: drop the ref so the chain is simply
            # absent (it re-prefills) rather than poisonous
            self.discard(key_hex)
            return None
        try:
            os.utime(path, None)                  # LRU touch
        except OSError:
            pass
        return data

    def discard(self, key_hex):
        try:
            os.unlink(self._ref_path(key_hex))
        except OSError:
            pass

    def _enforce_capacity(self, keep=None):
        while self.used_bytes > self.capacity_bytes:
            refs = []
            for key_hex in self.keys():
                if key_hex == keep:
                    continue
                try:
                    refs.append((os.path.getmtime(self._ref_path(key_hex)),
                                 key_hex))
                except OSError:
                    continue
            if not refs:
                break
            refs.sort()
            self.discard(refs[0][1])
            self.gc()

    def gc(self):
        """Drop chunks no ref file points at; returns bytes freed."""
        live = set()
        for key_hex in self.keys():
            try:
                with open(self._ref_path(key_hex), encoding="ascii") as f:
                    live.add(f.read().strip())
            except OSError:
                continue
        _, freed = self._chunks.gc(live)
        return freed

    def check_integrity(self):
        bad = []
        have = set(self._chunks.digests())
        for key_hex in self.keys():
            try:
                with open(self._ref_path(key_hex), encoding="ascii") as f:
                    digest = f.read().strip()
            except OSError:
                continue
            if digest not in have:
                bad.append("disk ref %s.. -> missing chunk %s.."
                           % (key_hex[:12], digest[:12]))
        return bad


class TieredKVStore:
    """The demote/promote stack behind one decode scheduler's HBM pool.

    Keys are the pool's raw sha256 chain keys (bytes); internally and
    on disk they are hex.  ``observer`` is duck-typed (DecodeMetrics):
    ``record_tier_demotion(tier, nbytes)``,
    ``record_tier_promotion(tier, nbytes)`` and ``record_disk_readmit()``
    are called as blocks move — absent methods are simply skipped.
    ``version`` bumps on every mutation so advertisement snapshots can
    be rebuilt only when something actually changed.
    """

    def __init__(self, host_bytes=0, disk_dir=None, disk_bytes=0,
                 observer=None):
        if not host_bytes and not disk_dir:
            raise ValueError("tiered KV store needs a host-RAM byte "
                             "budget, a disk directory, or both")
        self.host = HostTier(host_bytes) if host_bytes else None
        self.disk = DiskTier(disk_dir, disk_bytes) if disk_dir else None
        self.observer = observer
        self.version = 0
        # cumulative counters (mirrors of what the observer sees, so
        # stats work without a metrics registry wired in)
        self.demotions = {"host": 0, "disk": 0}
        self.promotions = {"host": 0, "disk": 0}
        self.disk_readmits = 0

    # -- helpers -------------------------------------------------------------
    @staticmethod
    def _hex(key):
        return key.hex() if isinstance(key, (bytes, bytearray)) else str(key)

    def _note(self, method, *args):
        fn = getattr(self.observer, method, None)
        if fn is not None:
            fn(*args)

    # -- data path -----------------------------------------------------------
    def demote(self, key, data):
        """Park one serialized block evicted from HBM.  Returns the
        tier it landed in ('host' or 'disk')."""
        self.version += 1
        key_hex = self._hex(key)
        if self.host is not None:
            spilled = self.host.put(key_hex, data)
            self.demotions["host"] += 1
            self._note("record_tier_demotion", "host", len(data))
            for k, v in spilled:
                if self.disk is not None:
                    self.disk.put(k, v)
                    self.demotions["disk"] += 1
                    self._note("record_tier_demotion", "disk", len(v))
            return "host"
        self.disk.put(key_hex, data)
        self.demotions["disk"] += 1
        self._note("record_tier_demotion", "disk", len(data))
        return "disk"

    def lookup(self, key):
        """``(tier_name, data)`` for a resident chain key, else None.

        Touch-promotes: a host hit refreshes its LRU slot; a disk hit
        is copied up into the host tier (the caller is about to readmit
        it into HBM, making it the hottest chain in the store)."""
        key_hex = self._hex(key)
        if self.host is not None:
            data = self.host.get(key_hex)
            if data is not None:
                self.promotions["host"] += 1
                self._note("record_tier_promotion", "host", len(data))
                return "host", data
        if self.disk is not None:
            data = self.disk.get(key_hex)
            if data is not None:
                self.version += 1
                self.disk_readmits += 1
                self.promotions["disk"] += 1
                self._note("record_tier_promotion", "disk", len(data))
                self._note("record_disk_readmit")
                if self.host is not None:
                    for k, v in self.host.put(key_hex, data):
                        if k != key_hex:          # don't spill it back out
                            self.disk.put(k, v)
                            self.demotions["disk"] += 1
                            self._note("record_tier_demotion", "disk",
                                       len(v))
                return "disk", data
        return None

    def tier_of(self, key):
        key_hex = self._hex(key)
        if self.host is not None and key_hex in self.host:
            return "host"
        if self.disk is not None and key_hex in self.disk:
            return "disk"
        return None

    # -- introspection -------------------------------------------------------
    def resident_keys(self):
        """{'host': [hex...], 'disk': [hex...]} of resident chains."""
        out = {}
        if self.host is not None:
            out["host"] = self.host.keys()
        if self.disk is not None:
            out["disk"] = self.disk.keys()
        return out

    def used_bytes(self):
        return {"host": self.host.used_bytes if self.host else 0,
                "disk": self.disk.used_bytes if self.disk else 0}

    def check_integrity(self):
        bad = []
        if self.host is not None:
            bad.extend(self.host.check_integrity())
        if self.disk is not None:
            bad.extend(self.disk.check_integrity())
        return bad

    def stats(self):
        used = self.used_bytes()
        out = {"demotions": dict(self.demotions),
               "promotions": dict(self.promotions),
               "disk_readmits": self.disk_readmits,
               "host_bytes": used["host"],
               "disk_bytes": used["disk"],
               "host_blocks": len(self.host) if self.host else 0,
               "disk_blocks": len(self.disk) if self.disk else 0}
        return out
