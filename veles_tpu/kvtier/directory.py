"""Fleet-wide prefix directory: which replica holds which chain.

Replicas advertise their resident chain keys — truncated hex, grouped
by tier — inside the ``load`` payload the router already polls off
``/readyz`` (no new control traffic).  The router feeds each probe into
a :class:`PrefixDirectory` and consults it per request: a client that
sends its prompt's chain keys (the ``X-Veles-Prefix-Keys`` header,
computed with the same rolling sha256 as the pools use) is routed to
the replica holding the **longest consecutive leading run** of those
keys, falling back to least-loaded when nobody holds anything or the
holder is not currently eligible.  Stale entries are harmless by
construction: affinity only ever *biases* the pick among eligible
replicas, and a miss on arrival degrades to a normal prefill.
"""

import threading

__all__ = ["PrefixDirectory", "PREFIX_HEADER", "prefix_key_header"]

#: request header carrying the prompt's chain keys (comma-separated
#: truncated hex, leading blocks first) for cache-aware routing
PREFIX_HEADER = "X-Veles-Prefix-Keys"

_TIER_RANK = {"hbm": 0, "host": 1, "disk": 2}


def prefix_key_header(tokens, block_size, max_keys=16):
    """Header value for a prompt: its chain keys in advertised form.

    Client-side helper (benches, tests): mirrors what the serving pool
    computes at admit, so the router can match the prompt against
    advertised residency without ever parsing the request body."""
    from ..serving.kvcache import key_chain     # lazy: avoids import cycle
    from .store import advert_key
    keys = key_chain(tokens, block_size)[:max_keys]
    return ",".join(advert_key(k) for k in keys)


class PrefixDirectory:
    """Thread-safe map of advertised chain keys per replica."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_replica = {}    # rid -> {key_hex: tier}

    def update(self, rid, tiers):
        """Replace ``rid``'s advertisement.  ``tiers`` maps tier name
        ('hbm' | 'host' | 'disk') to a list of truncated-hex keys; a
        key in several tiers records its fastest one."""
        keymap = {}
        for tier in ("hbm", "host", "disk"):
            for key in tiers.get(tier) or ():
                key = str(key)
                old = keymap.get(key)
                if old is None or _TIER_RANK[tier] < _TIER_RANK[old]:
                    keymap[key] = tier
        with self._lock:
            self._by_replica[str(rid)] = keymap

    def drop(self, rid):
        with self._lock:
            self._by_replica.pop(str(rid), None)

    def replicas(self):
        with self._lock:
            return list(self._by_replica)

    def best_replica(self, keys, candidates=None):
        """``(rid, matched)`` — the replica holding the longest
        consecutive leading run of ``keys`` (any tier), or (None, 0).
        ``candidates`` restricts the search to currently-eligible
        replica ids; ties break on the smaller rid for determinism."""
        keys = [str(k) for k in keys]
        best_rid, best_n = None, 0
        with self._lock:
            items = sorted(self._by_replica.items())
        for rid, keymap in items:
            if candidates is not None and rid not in candidates:
                continue
            n = 0
            for key in keys:
                if key not in keymap:
                    break
                n += 1
            if n > best_n:
                best_rid, best_n = rid, n
        return best_rid, best_n

    def residency(self, key):
        """{rid: tier} for one advertised key across the fleet."""
        key = str(key)
        out = {}
        with self._lock:
            for rid, keymap in self._by_replica.items():
                tier = keymap.get(key)
                if tier is not None:
                    out[rid] = tier
        return out

    def snapshot(self, max_keys=None):
        """Full directory for the ``/fleet/kv`` route: per replica, the
        advertised keys grouped back by tier (optionally capped)."""
        out = {}
        with self._lock:
            items = list(self._by_replica.items())
        for rid, keymap in items:
            tiers = {"hbm": [], "host": [], "disk": []}
            for key, tier in keymap.items():
                tiers[tier].append(key)
            for tier in tiers:
                tiers[tier].sort()
                if max_keys is not None:
                    tiers[tier] = tiers[tier][:max_keys]
            tiers["total"] = len(keymap)
            out[rid] = tiers
        return out
