"""Fleet-global tiered KV cache (HBM -> host RAM -> disk).

Per replica, :class:`TieredKVStore` catches chain blocks the HBM pool
would otherwise evict and parks them down a memory hierarchy; fleet
wide, :class:`PrefixDirectory` lets the router steer each request to
the replica already holding the longest resident prefix of its prompt
chain.  See docs/COMPONENTS.md "Tiered KV cache & cache-aware routing".
"""

from .store import (TieredKVStore, HostTier, DiskTier,
                    DIR_ENV, ADVERT_HEX, advert_key)
from .directory import PrefixDirectory, PREFIX_HEADER, prefix_key_header

__all__ = ["TieredKVStore", "HostTier", "DiskTier",
           "PrefixDirectory", "PREFIX_HEADER", "prefix_key_header",
           "DIR_ENV", "ADVERT_HEX", "advert_key"]
